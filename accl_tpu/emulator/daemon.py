"""Rank daemon: an out-of-process emulated device behind the socket protocol.

This is the Python twin of the reference's CPU emulator process
(test/emulation/cclo_emu.cpp): one process per rank, a command server for
the driver (reference: ZMQ REQ/REP, zmq_intf.cpp:166-291), and an eth
fabric between daemons (reference: ZMQ PUB/SUB frames, zmq_intf.cpp:70-164).
The native C++ daemon (native/cclo_emud.cpp) implements the same protocol;
the test corpus runs against either via ``SimDevice``.

Run one rank:  python -m accl_tpu.emulator.daemon --rank R --world W \
                      --port-base 45000
Ports: cmd = port_base + rank, eth = port_base + world + rank.
"""

from __future__ import annotations

import argparse
import itertools
import os
import socket
import struct
import threading
import time

import numpy as np

from ..arith import ArithConfig
from ..communicator import Communicator, Rank
from ..constants import (CCLOp, CfgFunc, CollectiveAlgorithm, Compression,
                         ErrorCode, ReduceFunc, StreamFlags)
from ..log import basic_config, get_logger
from ..plancache import PlanCache, cached_program
from ..tracing import METRICS, TRACE as _TRACE, health_rows

# daemon-instance tags for registry rows (cf. fabric._CTX_SEQ)
_DAEMON_CTX_SEQ = itertools.count(1)
from . import protocol as P
from .executor import DeviceMemory, MoveExecutor, RxBufferPool
from .fabric import Envelope

log = get_logger(__name__)


def _sane_budget(b: float, *, configured: bool = False) -> float:
    """Wait budgets arrive on the wire as attacker-controlled doubles:
    NaN/Inf/negative must not reach the wait machinery, where they would
    wedge the serving thread (mirrors the C++ daemon's sane_budget).
    ``configured`` marks a deliberate client setting (MSG_SET_TIMEOUT /
    CfgFunc.set_timeout): a finite value above the 1 h ceiling is then a
    user mistake worth surfacing, so the clamp is logged instead of
    silently shortening their waits."""
    if not (b >= 0.0):  # NaN and negatives
        if configured:
            # 0s means every wait times out immediately — the nastiest
            # surprise of the three coercions, never pass it silently
            log.warning(
                "configured timeout %r is not a non-negative number; "
                "coerced to 0s (immediate timeout)", b)
        return 0.0
    if b > 3600.0:
        if configured and b != float("inf"):
            log.warning(
                "configured timeout %.0fs exceeds the 3600s daemon "
                "ceiling; clamped to 3600s", b)
        return 3600.0
    return b


def stack_from_env(default: str = "tcp") -> str:
    """Eth-fabric selection for daemon worlds: ``$ACCL_TPU_FABRIC`` in
    {tcp, udp, shm} — shm is the shared-memory dataplane for co-located
    ranks (emulator/shm.py). Explicit ``stack=`` arguments win; the env
    var only fills defaults, so a test that pins a stack stays pinned."""
    stack = os.environ.get("ACCL_TPU_FABRIC", "") or default
    if stack not in ("tcp", "udp", "shm"):
        raise ValueError(
            f"$ACCL_TPU_FABRIC={stack!r}: want tcp, udp or shm")
    return stack


def _fabric_classes() -> dict:
    """stack name -> fabric class (lazy: shm.py imports back into this
    module for the embedded EthFabric and the shared landing verify)."""
    from .shm import ShmFabric
    return {"tcp": EthFabric, "udp": UdpEthFabric, "shm": ShmFabric}


def probe_peer_caps(host: str, port: int,
                    timeout: float = 0.3) -> int | None:
    """Best-effort capability probe of a peer daemon's COMMAND port: one
    MSG_GET_INFO round trip, returning the trailing caps word (0 for
    LEGACY daemons predating it — pre-caps builds whose replies are 38
    payload bytes; the current native ``cclo_emud`` advertises
    CAP_RETX_ACK and the crc32c csum bits like the python daemons), or
    None when the peer was unreachable within the budget (unknown, NOT
    zero: a still-starting daemon must not be mistaken for a legacy
    one)."""
    try:
        with socket.create_connection((host, port),
                                      timeout=timeout) as sock:
            sock.settimeout(timeout)
            P.send_frame(sock, bytes([P.MSG_GET_INFO]))
            reply = P.recv_frame(sock)
    except (OSError, ConnectionError, struct.error):
        return None
    if not reply or reply[0] != P.MSG_DATA:
        return None
    payload = reply[1:]
    if len(payload) >= 42:
        return struct.unpack("<I", payload[38:42])[0]
    return 0


def _env_from_eth_frame(frame: bytes) -> tuple[Envelope, bytes]:
    """Decode an eth frame (post-MSG_ETH byte) into (Envelope, payload) —
    shared by both fabric stacks so the header format lives in one place.
    A trailing integrity word (checksummed sender) rides into
    ``env.csum`` for the landing verify; frames from unchecksummed
    senders decode with ``csum=None`` and skip verification."""
    hdr, payload = P.unpack_eth(frame)
    env = Envelope(src=hdr["src"], dst=hdr["dst"], tag=hdr["tag"],
                   seqn=hdr["seqn"], nbytes=hdr["nbytes"],
                   wire_dtype=P.code_dtype(hdr["dtype"]).name,
                   strm=hdr["strm"], comm_id=hdr["comm_id"],
                   csum=hdr["csum"])
    return env, payload


def _verify_frame(env: Envelope, payload, fabric: str, stats: dict,
                  retx, latch_fn, enabled: bool = True,
                  stats_lock=None) -> bool:
    """Shared landing check for the socket fabrics (the LocalFabric
    twin lives on the fabric itself), covering pool-destined (strm=0)
    AND stream-port (strm=1) payloads — RMA lanes (4/5) are verified by
    the engine against its own NACK machinery, and the remaining lanes
    (ACK/heartbeat/join) are control frames the checksum tier does not
    cover. False = the payload failed its checksum and must be treated
    exactly like a drop. With a retransmission layer armed (UDP, strm=0)
    the frame stays UNACKED so the sender's RTO re-fetches the original;
    without one (TCP, retx_window=0, or the never-retransmitted stream
    lane) the typed DATA_INTEGRITY_ERROR latches per comm at verify
    time, surfacing in the pending recv's error word.

    ``enabled`` mirrors the fabric's own csum flag: a daemon with
    checksums off ($ACCL_TPU_CSUM=0) or pinned off at configure time
    (variant-mismatched peer) must stop VERIFYING too, not just stop
    emitting — its CRC variant may be the very thing that disagrees."""
    if not enabled or env.csum is None or env.strm > 1 \
            or P.csum_of(payload) == env.csum:
        return True
    if stats_lock is not None:
        # TCP runs one receive loop PER inbound connection: the
        # read-modify-write below would lose increments under
        # concurrent corruption drops (the UDP fabric's single recv
        # thread needs no lock). Failure path only — the clean path
        # returned above.
        with stats_lock:
            stats["integrity_failed"] = \
                stats.get("integrity_failed", 0) + 1
    else:
        stats["integrity_failed"] = stats.get("integrity_failed", 0) + 1
    METRICS.inc("integrity_failed_total", fabric=fabric,
                comm_id=env.comm_id, src=env.src, dst=env.dst)
    if _TRACE.enabled:
        _TRACE.emit("integrity_drop", rank=env.dst, seqn=env.seqn,
                    peer=env.src, nbytes=env.nbytes)
    if (retx is None or env.strm) and latch_fn is not None:
        latch_fn(env.comm_id, int(ErrorCode.DATA_INTEGRITY_ERROR))
    return False


def _apply_fault(fault_fn, env: Envelope, payload, fabric: str,
                 stats: dict, emit, sleep):
    """Shared chaos-action interpreter for the socket fabrics (the
    LocalFabric twin stays on the fabric: its zero-copy retransmission
    ring needs _track_lost interleaved with the actions). Returns the
    possibly-rewritten ``(env, payload)`` to emit, or ``None`` for a
    dropped frame; a ``duplicate`` emits the extra copy itself via
    ``emit``."""
    action = fault_fn(env, payload)
    flip_at = None
    if isinstance(action, tuple) and action:
        if action[0] == "delay":
            sleep(float(action[1]))
            action = "deliver"
        elif action[0] == "corrupt_payload":
            # targeted bit-flip (FaultRule.flip_at — e.g. a scale byte)
            flip_at = int(action[1])
            action = "corrupt_payload"
    if action == "drop":
        stats["fault_dropped"] = stats.get("fault_dropped", 0) + 1
        METRICS.inc("fabric_dropped_total", fabric=fabric,
                    comm_id=env.comm_id, src=env.src, dst=env.dst)
        return None
    if action == "corrupt_payload":
        # bit-flip AFTER the csum was computed (send()) — wire
        # corruption with an intact header; the receiver's landing
        # verify drops it, and on UDP the ring's retained ORIGINAL
        # payload rides the RTO resend
        from .fabric import flip_payload_bit
        METRICS.inc("fabric_corrupted_total", fabric=fabric,
                    comm_id=env.comm_id, src=env.src, dst=env.dst)
        payload = flip_payload_bit(payload, flip_at)
    elif action == "corrupt_seq":
        import dataclasses as _dc
        METRICS.inc("fabric_corrupted_total", fabric=fabric,
                    comm_id=env.comm_id, src=env.src, dst=env.dst)
        env = _dc.replace(env, seqn=env.seqn + 1_000_000)
    elif action == "duplicate":
        METRICS.inc("fabric_duplicated_total", fabric=fabric,
                    comm_id=env.comm_id, src=env.src, dst=env.dst)
        emit(env, payload)
    return env, payload


class EthFabric:
    """Daemon-to-daemon transport: one TCP connection per peer, lazily
    dialed; an accept loop ingests inbound frames.

    Emission is scatter-gather: header and payload leave in one
    ``sendmsg`` iovec (``protocol.send_frame_parts``) so a zero-copy
    payload view from the executor is never concatenated into a fresh
    frame buffer. ``$ACCL_TPU_COALESCE_BYTES`` > 0 additionally arms
    small-segment coalescing: frames below the watermark buffer per peer
    and flush as one write when the buffered bytes cross the watermark or
    the executor's egress runs dry (``MoveExecutor.flush_fn``) — the
    segment-streamed pipeline's answer to tiny-segment syscall storms.
    """

    # late caps re-probe hook (RankDaemon._presend_probe) — a CLASS
    # default so partially-constructed fabrics (unit-test stubs that
    # skip __init__) still send
    presend = None

    def __init__(self, my_global_rank: int, eth_port: int, ingest_fn):
        self.me = my_global_rank
        self.ingest = ingest_fn
        # per-peer (socket, lock): one slow peer's TCP backpressure must not
        # stall sends to other peers
        self._peers: dict[int, tuple[socket.socket, threading.Lock]] = {}
        self._peer_addrs: dict[int, tuple[str, int]] = {}
        self._inbound: list[socket.socket] = []  # accepted eth connections
        self._lock = threading.Lock()  # guards dial/lookup/inbound only
        self.coalesce = int(os.environ.get("ACCL_TPU_COALESCE_BYTES", "0"))
        self._txbuf: dict[int, list] = {}  # dst -> [nbytes, parts...]
        self.stats = {"sg_sends": 0, "coalesced_frames": 0, "flushes": 0,
                      "integrity_failed": 0, "fault_dropped": 0}
        # payload checksums ($ACCL_TPU_CSUM, default on): TCP is a
        # reliable stream but not an END-TO-END integrity proof — the
        # daemon process boundary, a buggy zero-copy emission, or a
        # chaos hook can still corrupt payload bytes between the two
        # rx pools. No retransmission layer exists on this stack, so a
        # failed landing verify latches typed DATA_INTEGRITY_ERROR per
        # comm (never a silent wrong result). Pinned off at configure
        # time when any peer lacks CAP_CSUM (RankDaemon._maybe_pin_caps).
        self.csum = P.csum_enabled_from_env()
        # chaos hook (message level, mirrors UdpEthFabric.inject_fault)
        self._fault = None
        self.latch_fn = None
        self._server = socket.create_server(("0.0.0.0", eth_port))
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def inject_fault(self, fault_fn):
        """Message-level fault hook (a :class:`~accl_tpu.chaos.FaultPlan`
        qualifies), applied on the send side to whole eth messages. The
        interesting kind on a reliable stream is ``corrupt_payload``:
        TCP re-delivers what it was handed, so corruption here proves
        the checksum tier's typed surfacing (no retransmission layer
        exists to heal it)."""
        self._fault = fault_fn

    def clear_fault(self):
        self._fault = None

    def learn_peers(self, ranks: list[tuple[int, str, int]], world: int):
        """Record peers' eth endpoints from a communicator table (cmd port
        table; eth port = cmd port + world)."""
        with self._lock:
            for grank, host, port in ranks:
                if grank != self.me and port:
                    self._peer_addrs[grank] = (host, port + world)

    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            with self._lock:
                self._inbound.append(conn)
            threading.Thread(target=self._recv_loop, args=(conn,),
                             daemon=True).start()

    def _recv_loop(self, conn: socket.socket):
        # buffered framing via the protocol's shared reader: back-to-back
        # eth frames (pipelined sends, ring schedules) arrive in ~one
        # syscall instead of two per frame, and the framing invariants
        # (length header, oversize guard) live in one place
        f = conn.makefile("rb")
        try:
            while True:
                body = P.recv_frame_file(f)
                if body[0] != P.MSG_ETH:
                    continue
                env, payload = _env_from_eth_frame(body[1:])
                if not _verify_frame(env, payload, "tcp", self.stats,
                                     None, self.latch_fn, self.csum,
                                     stats_lock=self._lock):
                    continue  # corrupt-as-loss: typed latch, no pool
                self.ingest(env, payload)
        except (ConnectionError, OSError, ValueError):
            return
        finally:
            with self._lock:
                if conn in self._inbound:
                    self._inbound.remove(conn)
            conn.close()

    def _peer(self, dst: int) -> tuple[socket.socket, threading.Lock]:
        with self._lock:
            entry = self._peers.get(dst)
            if entry is None:
                host, port = self._peer_addrs[dst]
                sock = socket.create_connection((host, port), timeout=10)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                entry = (sock, threading.Lock())
                self._peers[dst] = entry
        return entry

    def send(self, env: Envelope, payload: bytes):
        if self.presend is not None:
            self.presend(env)
        if self.csum and env.csum is None and env.nbytes:
            env.csum = P.csum_of(payload)
        if self._fault is not None:
            # chaos hook BETWEEN csum computation and emission — wire
            # corruption by construction: the trailing word still
            # describes the original payload, so the receiver's verify
            # catches the flip
            faulted = _apply_fault(self._fault, env, payload, "tcp",
                                   self.stats, self._emit, time.sleep)
            if faulted is None:
                return
            env, payload = faulted
        self._emit(env, payload)

    def _emit(self, env: Envelope, payload):
        sock, peer_lock = self._peer(env.dst)
        nbytes = P.payload_nbytes(payload)
        hdr = P.pack_eth_header(env.src, env.dst, env.tag, env.seqn,
                                env.comm_id, env.strm,
                                P.dtype_code(env.wire_dtype), nbytes)
        # trailing integrity word (protocol.py): decoders predating it
        # slice the payload by nbytes and never see the extra 4 bytes
        tail = (struct.pack("<I", env.csum) if env.csum is not None
                else b"")
        if _TRACE.enabled:
            _TRACE.emit("wire_send", rank=env.src, seqn=env.seqn,
                        peer=env.dst, nbytes=nbytes)
        with peer_lock:
            if self.coalesce and len(hdr) + nbytes < self.coalesce:
                # watermark coalescing: length-prefix each frame (frames
                # are self-delimiting on the stream) and buffer. Payload
                # views must be snapshotted — the send() contract is
                # "serialized before return", and the executor reuses
                # arena scratch the moment send() comes back.
                buf = self._txbuf.setdefault(env.dst, [0])
                buf.append(struct.pack("<I",
                                       len(hdr) + nbytes + len(tail)))
                buf.append(hdr)
                buf.append(bytes(payload))
                if tail:
                    buf.append(tail)
                buf[0] += 4 + len(hdr) + nbytes + len(tail)
                self.stats["coalesced_frames"] += 1
                if buf[0] >= self.coalesce:
                    self._flush_locked(sock, env.dst)
                return
            self._flush_locked(sock, env.dst)  # keep wire order
            self.stats["sg_sends"] += 1
            parts = (hdr, payload, tail) if tail else (hdr, payload)
            P.send_frame_parts(sock, parts)

    def _flush_locked(self, sock: socket.socket, dst: int):
        """Caller holds the peer lock. The buffered parts are already
        copies (snapshotted at coalesce time), so one join + sendall is
        the simple, short-write- and IOV_MAX-proof flush — the syscall
        batching was the point, not avoiding this bounded copy."""
        buf = self._txbuf.get(dst)
        if not buf or buf[0] == 0:
            return
        self.stats["flushes"] += 1
        sock.sendall(b"".join(buf[1:]))
        del self._txbuf[dst]

    def flush(self, dst: int):
        """Push any coalesced frames for ``dst`` onto the wire (the
        executor's egress calls this when its reorder stage runs dry)."""
        if not self.coalesce or dst not in self._txbuf:
            return  # plain membership probe: GIL-atomic, no dial needed
        sock, peer_lock = self._peer(dst)
        with peer_lock:
            self._flush_locked(sock, dst)

    @property
    def listening(self) -> bool:
        return self._server.fileno() != -1

    @property
    def n_connected(self) -> int:
        with self._lock:
            return len(self._peers)

    def connect_all(self) -> int:
        """Eagerly dial every known peer, replacing the lazy per-send dial.

        Parity: the reference's openCon walks the communicator and opens a
        TCP session per peer before any traffic (ccl_offload_control.c:
        109-165). Returns an OR-able error word, 0 on success."""
        with self._lock:
            targets = {g: a for g, a in self._peer_addrs.items()
                       if g not in self._peers}
        err = 0
        for grank, (host, port) in targets.items():
            try:
                sock = socket.create_connection((host, port), timeout=10)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                err |= int(ErrorCode.OPEN_CON_NOT_SUCCEEDED)
                continue
            with self._lock:
                if grank in self._peers:   # lost a dial race with send()
                    sock.close()
                else:
                    self._peers[grank] = (sock, threading.Lock())
        return err

    def disconnect_all(self):
        """Close per-peer sessions; send() re-dials lazily afterwards."""
        with self._lock:
            peers, self._peers = self._peers, {}
        for sock, _ in peers.values():
            sock.close()

    def close(self):
        # shutdown-before-close: a thread blocked in accept() holds a kernel
        # reference that would keep the port bound after close() alone
        try:
            self._server.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._server.close()
        for sock, _ in self._peers.values():
            sock.close()
        # accepted inbound connections too: their recv threads reference
        # this fabric's ingest path, and a runtime stack swap must not
        # leave them delivering stale-stack traffic (or leak fds per swap)
        with self._lock:
            inbound = list(self._inbound)
        for conn in inbound:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            conn.close()


class UdpEthFabric:
    """Datagram transport with explicit packetization — the UDP stack of
    the dual-stack story (reference: VNx UDP, runtime-selectable vs TCP,
    accl.py:383-395).

    Where the TCP fabric rides stream framing, this one does what the
    reference's hardware does in HLS:
      * ``udp_packetizer`` parity: each eth message is chopped into
        <=MAX_PKT-byte datagrams, each carrying {msg_id, frag_idx,
        n_frags} ahead of the first fragment's eth header
        (udp_packetizer.cpp:24-84 header word + max_pktsize chopping;
        the reference's max packet is 1536B, ccl_offload_control.h:50).
      * ``udp_depacketizer``/``rxbuf_session`` parity: fragments are
        reassembled per (peer, msg_id) with out-of-order tolerance; only a
        complete message is ingested (rxbuf_session.cpp fragment->buffer
        assembly). Stale partial messages are garbage-collected, and drops
        surface as receive timeouts upstream — UDP semantics, detected by
        the same failure machinery the fault-injection tests exercise.
    """

    MAX_PKT = 1408          # fragment payload bytes (reference: 1536B MTU)
    _FRAG_FMT = "<IIHH"     # sender_rank, msg_id, frag_idx, n_frags
    PARTIAL_TTL = 30.0      # seconds before an incomplete message is GC'd
    QUEUE_DEPTH = 64        # per-sender delivery bound; beyond it messages
    # are DROPPED (UDP semantics): TCP's flow control does not exist here,
    # and an unbounded queue would grow without limit while the rx pool is
    # full. Drops are counted in ``stats["dropped_queue_full"]``; with the
    # reliability layer armed (default) the dropped message is simply not
    # acknowledged — the sender's RTO recovers it once the queue drains —
    # and with $ACCL_TPU_RETX_WINDOW=0 a typed FABRIC_QUEUE_OVERFLOW is
    # latched per comm AT DROP TIME (``latch_fn``), so the failure
    # surfaces as itself instead of as a generic recv timeout.

    # late caps re-probe hook (class default: see EthFabric.presend)
    presend = None

    def __init__(self, my_global_rank: int, eth_port: int, ingest_fn,
                 retx_window: int | None = None):
        import time as _t

        from .reliability import RetxEndpoint, retx_window_from_env
        self.me = my_global_rank
        self.ingest = ingest_fn
        self._time = _t
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8 << 20)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 8 << 20)
        self._sock.bind(("0.0.0.0", eth_port))
        self._peer_addrs: dict[int, tuple[str, int]] = {}
        self._lock = threading.Lock()
        self._msg_id = 0
        # (sender, msg_id) -> [deadline, n_frags, {idx: bytes}]
        self._partial: dict = {}
        self._queues: dict = {}  # sender -> delivery Queue (lazy workers)
        self._closing = False
        self._fault = None       # chaos hook (message-level, like Local)
        # typed drop latch (daemon wires the rx pool's latch_error):
        # surfaces deliver-queue drops per comm on the no-retx path
        self.latch_fn = None
        # selective retransmission over the genuinely lossy stack: the
        # sender's in-flight ring snapshots each eth message (the socket
        # path reuses caller scratch after send) and unacknowledged
        # messages retransmit on RTO. ACKs ride strm=ACK_STRM frames.
        window = (retx_window_from_env() if retx_window is None
                  else max(0, int(retx_window)))
        self.retx = None
        if window > 0:
            self.retx = RetxEndpoint(
                my_global_rank, resend_fn=self._resend,
                ack_fn=self._send_ack, window=window,
                latch_fn=lambda cid, err: (self.latch_fn(cid, err)
                                           if self.latch_fn else None),
                fabric="udp", copy_payloads=True)
        # payload checksums ($ACCL_TPU_CSUM, default on; pinned off at
        # configure time only when a LEGACY peer lacks CAP_CSUM — the
        # current native cclo_emud speaks crc32c, see
        # RankDaemon._maybe_pin_caps): a reassembled message whose
        # payload fails its trailing crc32 is dropped UNACKED, so the
        # sender's RTO re-fetches the original (corrupt-as-loss); at
        # retx_window=0 the drop latches typed DATA_INTEGRITY_ERROR
        # instead, mirroring the queue-overflow latch below
        self.csum = P.csum_enabled_from_env()
        # observable health of the lossy transport: a slow consumer shows
        # up here (bounded-queue drops) instead of as silent unbounded
        # memory growth
        self.stats = {"sent": 0, "delivered": 0, "dropped_queue_full": 0,
                      "gc_partials": 0, "fault_dropped": 0,
                      "integrity_failed": 0}
        # deliver-queue drops fold through a collector, not a per-event
        # registry inc: a slow consumer rejects EVERY frame of a large
        # collective, and taking the process-wide registry lock per drop
        # on the sole datagram thread is the same storm-shaped cost that
        # RankDaemon._rejections avoids. Single-writer per key (one
        # datagram RX thread); close() flushes the totals into the
        # registry so a torn-down fabric's drops stay diagnosable.
        self._drops: dict[tuple, int] = {}
        METRICS.register_collector(self, UdpEthFabric._drop_rows)
        threading.Thread(target=self._recv_loop, daemon=True).start()

    def _drop_rows(self):
        for (comm_id, src, dst), n in list(self._drops.items()):
            yield ("counter", "fabric_dropped_total",
                   {"fabric": "udp", "comm_id": comm_id, "src": src,
                    "dst": dst}, n)

    def learn_peers(self, ranks: list[tuple[int, str, int]], world: int):
        with self._lock:
            for grank, host, port in ranks:
                if grank != self.me and port:
                    self._peer_addrs[grank] = (host, port + world)

    # -- reliability / chaos ----------------------------------------------
    def inject_fault(self, fault_fn):
        """Message-level fault hook (``fault_fn(env, payload) -> action``,
        a :class:`~accl_tpu.chaos.FaultPlan` qualifies): applied on the
        send side to whole eth messages — drop / corrupt_seq / duplicate /
        ("delay", s) — so a seeded chaos schedule exercises the UDP
        stack's retransmission exactly like the in-process fabric's."""
        self._fault = fault_fn

    def clear_fault(self):
        self._fault = None

    def reset_reliability(self):
        if self.retx is not None:
            self.retx.reset()

    def reset_comm(self, comm_id: int):
        if self.retx is not None:
            self.retx.reset_comm(comm_id)

    def _send_ack(self, dst_grank: int, comm_id: int, cum: int, sel):
        env = Envelope(src=self.me, dst=dst_grank, tag=0, seqn=cum,
                       nbytes=0, wire_dtype="uint8", strm=P.ACK_STRM,
                       comm_id=comm_id)
        try:
            self._wire_send(env, P.pack_ack(cum, sel))
        except (KeyError, OSError):
            pass  # peer unknown / socket closing: the sender's RTO covers

    def _resend(self, env: Envelope, payload):
        """Retransmission path: re-packetize the stored message (fresh
        msg_id — reassembly is per (sender, msg_id); dedup is by envelope
        seqn at the receiver's reliability tracker)."""
        self._wire_send(env, payload)

    def send(self, env: Envelope, payload: bytes):
        if self.presend is not None:
            self.presend(env)
        if self.csum and env.csum is None and P.payload_nbytes(payload):
            # before track(): the ring stores this envelope, so an RTO
            # resend re-emits the SAME valid integrity word over the
            # retained original payload
            env.csum = P.csum_of(payload)
        if self.retx is not None and not env.strm:
            self.retx.track(env, payload)
        self._wire_send(env, payload)

    def _wire_send(self, env: Envelope, payload):
        # the fault hook sees data AND heartbeat frames (a partition
        # must silence membership exactly like data — the documented
        # contract); only ACK control frames are exempt, so a chaos
        # schedule can never turn recovery against itself
        if self._fault is not None and env.strm != P.ACK_STRM:
            faulted = _apply_fault(self._fault, env, payload, "udp",
                                   self.stats, self._wire_frags,
                                   self._time.sleep)
            if faulted is None:
                return
            env, payload = faulted
        self._wire_frags(env, payload)

    def _wire_frags(self, env: Envelope, payload):
        nbytes = P.payload_nbytes(payload)
        # scatter-gather packetization: the eth header, (memoryview
        # slices of) the payload, and the optional trailing integrity
        # word ride each datagram's sendmsg iovec — the old path
        # concatenated header+payload AND re-sliced the result, two full
        # copies per message
        eth_hdr = memoryview(P.pack_eth_header(
            env.src, env.dst, env.tag, env.seqn, env.comm_id, env.strm,
            P.dtype_code(env.wire_dtype), nbytes))[1:]
        regions = [eth_hdr, memoryview(payload).cast("B")]
        if env.csum is not None:
            regions.append(memoryview(
                struct.pack("<I", env.csum & 0xFFFFFFFF)))
        with self._lock:
            addr = self._peer_addrs[env.dst]
            msg_id = self._msg_id
            self._msg_id += 1
        total = sum(len(r) for r in regions)
        n_frags = max(1, -(-total // self.MAX_PKT))
        sendmsg = getattr(self._sock, "sendmsg", None)  # test stubs may
        # expose only the classic sendto interface
        for idx in range(n_frags):
            start = idx * self.MAX_PKT
            end = min(total, start + self.MAX_PKT)
            parts = [struct.pack(self._FRAG_FMT, self.me, msg_id, idx,
                                 n_frags)]
            off = 0
            for r in regions:
                lo, hi = max(start, off), min(end, off + len(r))
                if lo < hi:
                    parts.append(r[lo - off:hi - off])
                off += len(r)
            if sendmsg is not None:
                sendmsg(parts, [], 0, addr)
            else:
                self._sock.sendto(b"".join(parts), addr)
        self.stats["sent"] += 1
        if _TRACE.enabled:
            _TRACE.emit("wire_send", rank=env.src, seqn=env.seqn,
                        peer=env.dst, nbytes=nbytes)

    def _recv_loop(self):
        hdr_len = struct.calcsize(self._FRAG_FMT)
        while True:
            try:
                dgram, _ = self._sock.recvfrom(self.MAX_PKT + hdr_len + 64)
            except OSError:
                return
            try:
                self._on_datagram(dgram, hdr_len)
            except Exception:  # noqa: BLE001 — a malformed datagram (the
                # socket is wildcard-bound) must not kill the fabric's only
                # receive thread; UDP semantics allow dropping it
                log.error("rank %d udp fabric: malformed datagram dropped",
                          self.me, exc_info=True,
                          extra={"rank": self.me})

    def _on_datagram(self, dgram: bytes, hdr_len: int):
        if len(dgram) < hdr_len:
            return
        sender, msg_id, idx, n_frags = struct.unpack(
            self._FRAG_FMT, dgram[:hdr_len])
        chunk = dgram[hdr_len:]
        key = (sender, msg_id)
        now = self._time.monotonic()
        entry = self._partial.setdefault(
            key, [now + self.PARTIAL_TTL, n_frags, {}])
        entry[2][idx] = chunk
        if len(entry[2]) == entry[1]:           # complete
            del self._partial[key]
            frame = b"".join(entry[2][i] for i in range(entry[1]))
            env, payload = _env_from_eth_frame(frame)
            if env.strm == P.ACK_STRM:
                # reliability control plane: never reaches the pool
                if self.retx is not None:
                    cum, sel = P.unpack_ack(payload)
                    self.retx.on_ack(env.src, env.comm_id, cum, sel)
                return
            if not _verify_frame(env, payload, "udp", self.stats,
                                 self.retx, self.latch_fn, self.csum):
                # corrupt-as-loss, BEFORE the freshness check: the
                # tracker must never record a corrupt frame's seqn (it
                # would dedup-drop the retransmission of the original).
                # Unacked with retx armed -> the sender's RTO recovers;
                # typed latch at retx_window=0.
                return
            if self.retx is not None and not env.strm \
                    and not self.retx.fresh(env):
                # duplicate (raced its own ACK) or out-of-horizon
                # garbage: filtered before it can occupy an rx buffer
                return
            # per-sender delivery queues: ingest (which blocks while the
            # rx pool is full) must not head-of-line-block fragments from
            # OTHER peers behind the single recv thread
            q = self._deliver_q(env.src)
            if q is not None:
                import queue as _queue
                try:
                    q.put_nowait((env, payload))
                    if self.retx is not None and not env.strm:
                        # acknowledge only what was actually delivered:
                        # a queue-full drop below stays unacked so the
                        # sender's RTO recovers it
                        self.retx.record(env)
                except _queue.Full:
                    # bounded queue: drop (UDP semantics) — but COUNT it,
                    # so a slow consumer is diagnosable from stats
                    # instead of only from downstream recv timeouts
                    # (collector-folded, see __init__)
                    self.stats["dropped_queue_full"] += 1
                    k = (env.comm_id, env.src, env.dst)
                    # fabric-local lock (NOT the registry's process-wide
                    # one): close() swaps _drops out under the same lock,
                    # so a racing drop can neither be flushed twice nor
                    # lost between the flush and the collector
                    with self._lock:
                        self._drops[k] = self._drops.get(k, 0) + 1
                    if self.retx is None and self.latch_fn is not None:
                        # pre-retransmit fallback ($ACCL_TPU_RETX_WINDOW
                        # =0): the receiver used to just hang to its
                        # deadline — latch the typed per-comm error AT
                        # DROP TIME so the failure surfaces as itself
                        self.latch_fn(env.comm_id,
                                      int(ErrorCode.FABRIC_QUEUE_OVERFLOW))
        # GC stale partials (lost fragments must not leak memory)
        stale = [k for k, e in self._partial.items() if e[0] < now]
        for k in stale:
            del self._partial[k]
        self.stats["gc_partials"] += len(stale)

    def _deliver_q(self, sender: int):
        with self._lock:
            if self._closing:
                return None
            q = self._queues.get(sender)
            if q is None:
                import queue as _queue
                q = _queue.Queue(maxsize=self.QUEUE_DEPTH)
                self._queues[sender] = q

                def drain():
                    while True:
                        item = q.get()
                        if item is None:
                            return
                        self.ingest(*item)
                        self.stats["delivered"] += 1

                threading.Thread(target=drain, daemon=True).start()
        return q

    @property
    def listening(self) -> bool:
        return self._sock.fileno() != -1

    @property
    def n_connected(self) -> int:
        return 0

    def connect_all(self) -> int:
        """Datagram stack: no sessions to open (VNx UDP parity — openCon is
        a TCP-stack concept; the reference's UDP path programs a socket
        table instead, test_vnx.py:59-77)."""
        return 0

    def disconnect_all(self):
        pass

    def close(self):
        import queue as _queue
        with self._lock:
            flush = not self._closing
            self._closing = True
            queues = list(self._queues.values())
            # swap under the same lock the RX thread increments under:
            # a drop racing close() lands wholly in the old dict (flushed
            # once below) or wholly in the new one (collector-reported)
            drops: dict[tuple, int] = {}
            if flush:
                drops, self._drops = self._drops, {}
        if flush:
            # hand the folded drop totals to the registry directly: the
            # collector vanishes with this (weakly-held) fabric, but its
            # drops must stay diagnosable after world teardown
            for (comm_id, src, dst), n in drops.items():
                METRICS.inc("fabric_dropped_total", n, fabric="udp",
                            comm_id=comm_id, src=src, dst=dst)
        try:  # unblock the recvfrom thread so the port frees promptly
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
        for q in queues:
            # drain-then-sentinel: a FULL bounded queue must neither hang
            # shutdown (blocking put) nor swallow the sentinel (which would
            # leak the drain thread and its queued payloads forever)
            while True:
                try:
                    q.put_nowait(None)
                    break
                except _queue.Full:
                    try:
                        q.get_nowait()
                    except _queue.Empty:
                        pass


class RankDaemon:
    """One emulated rank: memory + pool + executor + async call queue."""

    def __init__(self, rank: int, world: int, port_base: int,
                 nbufs: int = 16, bufsize: int = 1 << 20,
                 host: str = "0.0.0.0", stack: str | None = None):
        self.rank = rank
        self.world = world
        self.port_base = port_base
        self.stack = stack = stack or stack_from_env()
        self.mem = DeviceMemory()
        self.pool = RxBufferPool(nbufs, bufsize)
        # multi-tenant service attribution: comm -> tenant from the
        # MSG_CONFIG_COMM tenant field; shared BY REFERENCE with the rx
        # pool. Per-tenant rx reservations come from $ACCL_TPU_RX_RESERVE
        # ("tenantA:4,tenantB:2") — daemons have no in-process
        # ServiceConfig to read, so the knob is environmental.
        self.comm_tenants: dict[int, str] = {}
        self.pool.tenant_of = self.comm_tenants
        self.rx_quota = None
        reserve = os.environ.get("ACCL_TPU_RX_RESERVE", "")
        if reserve:
            from ..service import QuotaManager, parse_reservations
            self.rx_quota = QuotaManager(nbufs,
                                         parse_reservations(reserve))
            self.pool.quota = self.rx_quota
        self.bufsize = bufsize
        self.timeout = 30.0
        self.max_segment_size = bufsize
        self.comms: dict[int, Communicator] = {}
        # compiled-plan cache (accl_tpu/plancache.py): the Python daemon
        # pays the same per-call expand+plan control-plane floor the
        # in-process tier does — ~230us/call at small sizes — and the
        # same (shape-keyed, epoch-invalidated) cache removes it
        self.plan_cache = PlanCache()
        self.comm_epoch = 0
        # bind the cmd port before the eth fabric / worker thread so a
        # port collision fails before any resources need cleanup
        self._server = socket.create_server((host, port_base + rank))
        try:
            # multi-stack parity: TCP (stream framing), UDP (datagram
            # packetizer/reassembly) — runtime-selectable like the
            # reference's use_tcp/use_udp (accl.py:383-395) — or SHM
            # (shared-memory ring buffers between co-located ranks,
            # with the TCP fabric embedded for per-link degradation)
            fabric_cls = _fabric_classes()[stack]
            self.eth = fabric_cls(rank, port_base + world + rank,
                                  self._ingest)
        except Exception:  # OverflowError for out-of-range ports, OSError...
            self._server.close()
            raise
        # one-sided RMA (accl_tpu/rma): window registry + put/get engine.
        # send_fn late-binds self.eth — a runtime stack swap
        # (set_stack_type) must route later frames through the new fabric
        from ..call import CallHandle as _CallHandle
        from ..rma import RmaEngine, WindowRegistry
        self._CallHandle = _CallHandle
        self.windows = WindowRegistry(owner=f"daemon rank {rank}")
        self.rma = RmaEngine(
            rank, self.mem, self.windows,
            lambda env, p: self.eth.send(env, p),
            pool_fn=lambda: self.pool, comm_of=self.comms.get,
            tenant_of=lambda cid: (self.comm_tenants.get(cid)
                                   or f"comm-{cid}"),
            timeout_fn=lambda: self.timeout,
            seg_fn=lambda: self.max_segment_size, tier="daemon",
            csum_fn=lambda: getattr(self.eth, "csum", False))
        self.executor = MoveExecutor(self.mem, self.pool, self.eth.send,
                                     timeout=self.timeout)
        # both eth fabrics serialize the payload into a frame before
        # send() returns, so emission may hand over zero-copy views of
        # device memory instead of paying the tobytes() copy
        self.executor.tx_serializes = True
        self.executor.owner_rank = rank
        self._wire_flush()
        self._wire_latch()
        # capability probing (PR 11/13/14): per-(host, cmd-port) caps
        # cache, peers whose configure-time probe FAILED (unknown — re-
        # probed lazily at first send toward them via the fabric presend
        # hook, closing the pre-probe window where a slow-starting
        # native peer could receive checksummed frames forever), and the
        # per-peer re-probe cooldown so a genuinely dead peer costs at
        # most one short probe per second on the send path
        self._peer_caps: dict[tuple, int] = {}
        self._unprobed: dict[int, tuple[str, int]] = {}
        self._probe_retry_at: dict[int, float] = {}
        # membership: heartbeat-based peer-failure detection, armed via
        # $ACCL_TPU_HEARTBEAT_MS (0 = off, the default). Peers are only
        # tracked once heard from (no false deaths during bring-up);
        # a silent peer past the missed-beat budget latches PEER_FAILED
        # per comm containing it and fast-aborts waiting programs.
        self.hb_interval = max(
            0.0, int(os.environ.get("ACCL_TPU_HEARTBEAT_MS", "0")) / 1e3)
        self.hb_budget = max(1, int(os.environ.get(
            "ACCL_TPU_HEARTBEAT_BUDGET", "3")))
        self._peer_last: dict[int, float] = {}
        self.dead_peers: set[int] = set()
        # elastic-membership join handshake (MSG_JOIN, the daemon twin
        # of EmuDevice.join_handshake): hellos heard per grown comm —
        # cleared at MSG_CONFIG_COMM, so the evidence's lifetime is
        # exactly one membership generation
        self._join_cv = threading.Condition()
        self._join_heard: dict[int, dict[int, int]] = {}
        # unified metrics: this daemon's health surfaces (eth fabric
        # stats, rx-pool occupancy, executor pipeline counters, plan
        # cache) polled only at snapshot time; the weak registration
        # dies with the daemon. ctx_seq keeps two in-process daemon
        # worlds' rank+tier series apart (cf. LocalFabric.ctx_seq)
        self.ctx_seq = next(_DAEMON_CTX_SEQ)
        METRICS.register_collector(self, _daemon_metrics_rows)
        # eager-ingress rejection counts, (peer, comm_id) -> n, folded in
        # by the collector above. Daemon-local on purpose: a starved pool
        # rejects EVERY segment of a collective, and a process-wide
        # registry lock on that path is the same per-event cost that
        # measurably skewed the small-message ladder in the driver (see
        # ACCL._metrics_rows). Single-writer per key — each peer's frames
        # arrive on that peer's RX thread (TCP) or the one datagram
        # thread (UDP), and the key leads with the peer.
        self._rejections: dict[tuple, int] = {}
        # eager-ingress rejection log rate limiter: src -> [window_start,
        # suppressed-in-window] — a starved rx pool rejects every message
        # of a big collective; one line per second per peer keeps stderr
        # readable while still reporting the total
        self._rej_log: dict[int, list] = {}
        # runtime config-call state (ACCL_CONFIG parity, c:1240-1283):
        # pkt engines default-armed so a daemon is usable without the
        # driver's bring-up sequence; profiling counters are in-daemon,
        # distinct from the host-side Profiler
        self.pkt_enabled = True
        self.profiling = False
        self.profiled_calls = 0
        self.profile_time = 0.0
        self._arrays: dict[int, np.ndarray] = {}
        # internal scratch for barrier (1-element allreduce rendezvous);
        # reserved address far above the driver's 4K-aligned bump allocator
        self._barrier_addr = 1 << 60
        self._barrier_scratch = np.zeros(2, np.float32)
        self.mem.register(self._barrier_addr, self._barrier_scratch)
        # async call tracking (hostctrl ap_ctrl_chain parity)
        self._next_call_id = 1
        # >0 while the worker (or an inline conn-thread execution) is
        # running a call: the conn-thread fast path may only run when
        # global FIFO order is provable (queue empty + nothing running)
        self._executing = 0
        self._call_status: dict[int, int | None] = {}
        # ids a blocked MSG_WAIT is sleeping on (waiter counts): these
        # entries are immune to the status-map eviction
        self._wait_active: dict[int, int] = {}
        # highest retired-status id the eviction dropped: MSG_WAIT
        # resolves ids at/below it from _failed_calls (FIFO retirement)
        self._evicted_max = 0
        # failed calls persist past their MSG_WAIT (which pops the
        # status): a call chained via wire waitfor must observe its
        # dependency's failure even after the client polled it. Bounded
        # FIFO — ancient failures age out.
        self._failed_calls: dict[int, int] = {}
        # highest FAILED id the bounded FIFO above aged out: a deferred
        # MSG_WAIT for an id at/below this mark cannot distinguish
        # success from an evicted failure — it must answer
        # CALL_OUTCOME_UNKNOWN, never fabricate a 0
        self._failed_evicted_max = 0
        self._call_cv = threading.Condition()
        self._call_queue: list[tuple[int, dict]] = []
        self._stop = threading.Event()
        threading.Thread(target=self._call_worker, daemon=True).start()
        if self.hb_interval > 0:
            threading.Thread(target=self._heartbeat_loop, daemon=True,
                             name=f"hb-rank{rank}").start()

    def _wire_latch(self):
        """Give the fabric a typed per-comm error latch into the CURRENT
        rx pool (a closure over ``self.pool``: soft reset swaps the pool
        object, and a bound method of the old one would latch into the
        corpse)."""
        self.eth.latch_fn = lambda cid, err: self.pool.latch_error(cid,
                                                                   err)

    def _caps_wanted(self) -> bool:
        """Does this daemon's live fabric state still depend on peer
        capabilities? Retransmission pinning (UDP), checksum pinning
        (any stack still emitting), and shm link upgrades (ShmFabric:
        every un-upgraded link is a candidate)."""
        return ((self.stack == "udp"
                 and getattr(self.eth, "retx", None) is not None)
                or getattr(self.eth, "csum", False)
                or bool(getattr(self.eth, "shm", False)))

    def _maybe_pin_caps(self, ranks):
        """Auto-pin capabilities down to each peer's answer at configure
        time — the moment peers become known — so mixed worlds degrade
        gracefully with no operator env var:

        * retransmission (UDP stack): a LEGACY peer with no ACK
          responder (pre-caps daemon builds) would RTO-storm
          retransmits to the give-up bound and latch false
          PEER_FAILED — a peer without CAP_RETX_ACK pins this daemon's
          retx window to 0 (``ACCL_TPU_RETX_WINDOW=0`` silences). The
          current native ``cclo_emud`` advertises CAP_RETX_ACK (full
          cum+selective ack responder), so mixed py/native worlds keep
          retransmitting end-to-end.
        * payload checksums (every stack): a peer without CAP_CSUM
          neither appends nor verifies the trailing integrity word;
          sending checksummed frames AT it is harmless (old decoders
          ignore trailing bytes) but its own frames arrive
          unverifiable — the world degrades to unchecksummed frames,
          with a one-time warning + ``csum_pinned_total``
          (``ACCL_TPU_CSUM=0`` silences). The current native daemon
          advertises CAP_CSUM | CAP_CSUM_C (crc32c, bit-identical to
          google-crc32c), so only genuinely legacy peers pin this.
        * shm links (PR 14): a SAME-HOST peer advertising CAP_SHM
          upgrades its one link to the shared-memory ring; every other
          peer stays on the embedded TCP fabric, per link
          (``shm_link_pinned_total`` counts the degradations).

        Each peer's cmd port is probed once (MSG_GET_INFO caps word,
        :func:`probe_peer_caps`), cached per (host, port). A peer
        UNREACHABLE at configure time is unknown, NOT zero (a still-
        starting Python daemon must not be mistaken for native) — it is
        recorded in ``_unprobed`` and re-probed at the FIRST SEND toward
        it (the fabric ``presend`` hook), so the pre-probe window closes
        at first traffic instead of waiting for a reconfigure that may
        never come."""
        if not self._caps_wanted():
            return
        for grank, host, port in ranks:
            if grank == self.rank or not port:
                continue
            key = (host, port)
            caps = self._peer_caps.get(key)
            if caps is None:
                caps = probe_peer_caps(host, port)
                if caps is None:
                    # unknown — cache the FAILURE and arm the late
                    # first-send re-probe; never pin on a guess
                    self._unprobed[grank] = key
                    self._arm_presend()
                    continue
                self._peer_caps[key] = caps
            self._unprobed.pop(grank, None)
            self._apply_peer_caps(grank, host, port, caps)

    def _apply_peer_caps(self, grank: int, host: str, port: int,
                         caps: int):
        """Fold one peer's probed caps word into this daemon's live
        fabric state (shared by the configure-time walk and the
        first-send late probe)."""
        if self.stack == "udp" \
                and getattr(self.eth, "retx", None) is not None \
                and not caps & P.CAP_RETX_ACK:
            log.warning(
                "rank %d: peer rank %d at %s:%d has no "
                "retransmission ACK responder (a legacy pre-caps "
                "daemon build) — pinning this daemon's retx "
                "window to 0 so retransmits toward it cannot "
                "RTO-storm into a false PEER_FAILED "
                "(set ACCL_TPU_RETX_WINDOW=0 to silence)",
                self.rank, grank, host, port,
                extra={"rank": self.rank})
            METRICS.inc("retx_pinned_total", rank=self.rank,
                        tier="daemon")
            self.eth.retx = None
        if getattr(self.eth, "csum", False) and \
                caps & (P.CAP_CSUM | P.CAP_CSUM_C) != P.csum_caps():
            # no checksums at all (legacy pre-caps daemon builds)
            # OR a different CRC variant (mixed installs: one side
            # has the hardware crc32c binding, the other does not) —
            # either way this daemon must stop emitting/verifying,
            # or a variant mismatch would reject every frame
            log.warning(
                "rank %d: peer rank %d at %s:%d does not speak "
                "this daemon's payload-checksum variant (%s; "
                "a legacy daemon build or a mixed install) — "
                "pinning checksums off so the world "
                "degrades to unchecksummed frames "
                "(set ACCL_TPU_CSUM=0 to silence)",
                self.rank, grank, host, port, P.CSUM_VARIANT,
                extra={"rank": self.rank})
            METRICS.inc("csum_pinned_total", rank=self.rank,
                        tier="daemon")
            self.eth.csum = False
        if getattr(self.eth, "shm", False) \
                and self.eth.link_of(grank) != "shm":
            if caps & P.CAP_SHM:
                if not self.eth.set_link(grank, "shm"):
                    # CAP_SHM but a different host: the segment name
                    # space does not span machines — socket path
                    METRICS.inc("shm_link_pinned_total", rank=self.rank,
                                peer=grank, reason="cross_host")
            else:
                METRICS.inc("shm_link_pinned_total", rank=self.rank,
                            peer=grank, reason="caps")
                log.info(
                    "rank %d shm: peer rank %d at %s:%d does not serve "
                    "the shared-memory dataplane — that link rides the "
                    "embedded TCP fabric", self.rank, grank, host, port,
                    extra={"rank": self.rank})

    def _arm_presend(self):
        """Install the first-send late caps probe on the current fabric
        (idempotent; _set_stack re-arms on the replacement fabric)."""
        if getattr(self.eth, "presend", None) is None:
            self.eth.presend = self._presend_probe

    def _presend_probe(self, env):
        """Fabric presend hook: a peer that was unreachable at configure
        time (unknown, NOT pinned) is re-probed here, on the first frame
        actually sent toward it — the PR-13 pre-probe window, where such
        a peer could receive checksummed frames forever, now closes at
        first traffic. Cooldown-bounded: a still-dead peer costs one
        short probe per second, on a send that is doomed anyway."""
        key = self._unprobed.get(env.dst)
        if key is None:
            return
        now = time.monotonic()
        if now < self._probe_retry_at.get(env.dst, 0.0):
            return
        self._probe_retry_at[env.dst] = now + 1.0
        caps = probe_peer_caps(key[0], key[1], timeout=0.2)
        if caps is None:
            return  # still unreachable; the next send past the cooldown
            # retries — never pin on a guess
        self._peer_caps[key] = caps
        self._unprobed.pop(env.dst, None)
        METRICS.inc("caps_probe_late_total", rank=self.rank,
                    peer=env.dst, tier="daemon")
        self._apply_peer_caps(env.dst, key[0], key[1], caps)
        if not self._unprobed:
            self.eth.presend = None  # hot path back to one branch

    # -- membership (heartbeats) -------------------------------------------
    def _heartbeat_loop(self):
        while not self._stop.wait(self.hb_interval):
            peers: set[int] = set()
            for comm in list(self.comms.values()):
                for r in comm.ranks:
                    if r.global_rank != self.rank and r.port:
                        peers.add(r.global_rank)
            for g in peers:
                env = Envelope(src=self.rank, dst=g, tag=0, seqn=0,
                               nbytes=0, wire_dtype="uint8",
                               strm=P.HB_STRM, comm_id=0)
                try:
                    self.eth.send(env, b"")
                except (KeyError, OSError, ConnectionError):
                    pass  # unreachable peer: exactly what the missed-
                    # beat budget is counting
            now = time.monotonic()
            for g, last in list(self._peer_last.items()):
                if g in self.dead_peers:
                    continue
                age = now - last
                if age > self.hb_interval:
                    METRICS.inc("heartbeat_missed_total", rank=self.rank,
                                peer=g, tier="daemon")
                if age > self.hb_interval * self.hb_budget:
                    self._peer_dead(g)

    def _note_heartbeat(self, grank: int):
        if grank in self.dead_peers:
            self.dead_peers.discard(grank)
            log.warning("rank %d: peer %d resumed heartbeats", self.rank,
                        grank, extra={"rank": self.rank})
        self._peer_last[grank] = time.monotonic()

    def _peer_dead(self, grank: int):
        self.dead_peers.add(grank)
        log.warning(
            "rank %d: peer %d missed %d heartbeats (%.0f ms budget) — "
            "declaring it dead, latching PEER_FAILED on its comms",
            self.rank, grank, self.hb_budget,
            self.hb_interval * self.hb_budget * 1e3,
            extra={"rank": self.rank})
        METRICS.inc("peer_failed_total", rank=self.rank, peer=grank,
                    tier="daemon")
        for cid, comm in list(self.comms.items()):
            if any(r.global_rank == grank for r in comm.ranks):
                self.pool.latch_error(cid, int(ErrorCode.PEER_FAILED))
        self.executor.fail_peer(grank, int(ErrorCode.PEER_FAILED))

    def _wire_flush(self):
        """Hand the executor's egress the fabric's coalescing flush hook
        (TCP fabric with $ACCL_TPU_COALESCE_BYTES armed; None otherwise,
        and on the UDP stack, which has nothing to coalesce)."""
        flush = getattr(self.eth, "flush", None)
        self.executor.flush_fn = (flush if flush is not None
                                  and getattr(self.eth, "coalesce", 0)
                                  else None)

    # -- elastic membership: join handshake (MSG_JOIN) ---------------------
    def _send_join(self, comm_id: int, dst: int, sig: int):
        env = Envelope(src=self.rank, dst=dst, tag=sig, seqn=0,
                       nbytes=0, wire_dtype="uint8", strm=P.JOIN_STRM,
                       comm_id=comm_id)
        try:
            self.eth.send(env, b"")
        except (KeyError, OSError, ConnectionError):
            pass  # unreachable joiner: the poll loop keeps trying and
            # the client's deadline types the failure

    def _join_step(self, comm_id: int, sig: int, budget: float) -> bytes:
        """One client-driven poll step of the join handshake: (re)send
        hellos to every peer of the grown comm, wait up to ``budget``
        for matching hellos from all of them. Replies 0 when complete
        (after broadcasting one final COMPLETION hello — a peer that
        configured, clearing its heard-table, after our last resend
        necessarily entered before we heard it, so the completion hello
        postdates its clear and closes that window), STATUS_PENDING
        while peers are missing (the client re-polls until ITS deadline
        types the failure), JOIN_FAILED on a membership-signature
        mismatch. Hellos are only ever sent from inside a handshake —
        never echoed from stored state — so a member that has not
        (re)entered the handshake for the current membership generation
        stays silent and a stale generation can never prove liveness."""
        comm = self.comms.get(comm_id)
        if comm is None:
            return P.status_reply(int(ErrorCode.COMM_NOT_CONFIGURED))
        peers = [r.global_rank for r in comm.ranks
                 if r.global_rank != self.rank]
        for g in peers:
            self._send_join(comm_id, g, sig)
        deadline = time.monotonic() + max(0.0, budget)
        while True:
            with self._join_cv:
                heard = self._join_heard.get(comm_id, {})
                if any(g in heard and heard[g] != sig for g in peers):
                    return P.status_reply(int(ErrorCode.JOIN_FAILED))
                if all(g in heard for g in peers):
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return P.status_reply(P.STATUS_PENDING)
                self._join_cv.wait(min(remaining, 0.02))
        # completion hello, sent 3x (independent loss coins on a lossy
        # wire — the emu tier's rationale in EmuDevice.join_handshake)
        for _ in range(3):
            for g in peers:
                self._send_join(comm_id, g, sig)
        return P.status_reply(0)

    # -- ingress -----------------------------------------------------------
    def _ingest(self, env: Envelope, payload: bytes):
        if env.strm == P.HB_STRM:
            self._note_heartbeat(env.src)
            return
        if env.strm == P.JOIN_STRM:
            # membership join hello: liveness-bearing (a rejoining peer
            # clears itself from the dead set) and stored for the
            # handshake poll. Deliberately NO echo from stored state —
            # only a member actively inside (or completing) a handshake
            # sends hellos, so stale pre-configure state can never
            # satisfy a fresh liveness proof (see _join_step)
            self._note_heartbeat(env.src)
            with self._join_cv:
                self._join_heard.setdefault(env.comm_id,
                                            {})[env.src] = env.tag
                self._join_cv.notify_all()
            return
        if env.strm in (P.RMA_STRM, P.RMA_DATA_STRM):
            # one-sided lanes: control frames + rendezvous segments (the
            # latter land directly in their registered window — never in
            # the rx pool; eager puts ride pool.ingest from inside the
            # engine, charging tenant quotas like any eager message)
            self.rma.on_frame(env, payload)
            return
        if env.strm >= 2:
            # reliability control frames never reach the stream ports
            # (the UDP fabric consumes its own ACKs; the TCP stack has
            # no retransmission — a stray ACK is dropped, not streamed)
            return
        if env.strm:
            self.executor.deliver_stream(env, payload)
            return
        err = self.pool.ingest(env, payload, timeout=self.timeout)
        if err:
            # every rejection counts (the LOG below is rate-limited; the
            # collector-folded counter is the accurate total, per
            # peer/comm/TENANT — so a noisy neighbor is identifiable
            # from metrics_text() alone; see __init__ for why not a
            # direct registry inc)
            tenant = (self.comm_tenants.get(env.comm_id)
                      or f"comm-{env.comm_id}")
            key = (env.src, env.comm_id, tenant)
            self._rejections[key] = self._rejections.get(key, 0) + 1
            # eager-ingress rejection is otherwise invisible until some
            # recv times out much later — say WHICH message died and why
            # (the latched word also rides into that recv's error word,
            # RxBufferPool.consume_error). Rate-limited to one line per
            # second per peer: a starved pool rejects EVERY segment of a
            # collective, and an unthrottled log would flood stderr
            # faster than the failure it reports.
            now = time.monotonic()
            ent = self._rej_log.setdefault(env.src, [-1e9, 0])
            if now - ent[0] < 1.0:
                ent[1] += 1
                return
            suppressed, ent[0], ent[1] = ent[1], now, 0
            log.warning(
                "rank %d eager ingress: rejected message from rank %d "
                "(tag=%d seqn=%d comm=%d tenant=%s, %d B): %s%s",
                self.rank, env.src, env.tag, env.seqn, env.comm_id,
                tenant, P.payload_nbytes(payload),
                " | ".join(e.name for e in ErrorCode
                           if e.value and err & e.value) or hex(err),
                f" (+{suppressed} more in the last second)"
                if suppressed else "", extra={"rank": self.rank})

    # -- call execution ----------------------------------------------------
    def _call_worker(self):
        while not self._stop.is_set():
            with self._call_cv:
                # also parks while a conn-thread inline execution is in
                # flight: two calls running concurrently would break the
                # FIFO retirement contract (and share the executor)
                while (not self._call_queue or self._executing) \
                        and not self._stop.is_set():
                    self._call_cv.wait(0.5)
                if self._stop.is_set():
                    return
                call_id, c = self._call_queue.pop(0)
                self._executing += 1
            # waitfor error propagation: the single worker retires FIFO,
            # so every wire-waitfor dependency has already retired — if
            # one failed, this call must not execute (in-process tier
            # parity: the worker's dep.wait raises)
            err = None
            for dep in c.get("waitfor", ()):
                dep_err = self._failed_calls.get(dep)
                if dep_err:
                    err = dep_err
                    break
            if err is None:
                t0 = time.perf_counter()
                err = self._execute(c)
                if self.profiling and c["scenario"] != int(CCLOp.config):
                    self.profiled_calls += 1
                    self.profile_time += time.perf_counter() - t0
            with self._call_cv:
                self._executing -= 1
                self._record_status(call_id, err)

    def _record_status(self, call_id: int, err: int):
        """Caller holds _call_cv."""
        self._call_status[call_id] = err
        if err:
            self._failed_calls[call_id] = err
            while len(self._failed_calls) > 1024:
                aged = next(iter(self._failed_calls))
                self._failed_calls.pop(aged)
                if aged > self._failed_evicted_max:
                    self._failed_evicted_max = aged
        # Bound the status map: a chain client that waits only the LAST
        # id (call_chain's documented pattern) would otherwise leak one
        # retired entry per unwaited link forever. At most ONE eviction
        # per insert keeps it bounded without a hot-path key copy, and
        # two classes are never evicted: None entries (in-flight calls)
        # and ids a blocked MSG_WAIT is actively sleeping on (evicting
        # those would turn a retired call into a spurious timeout).
        if len(self._call_status) > 4096:
            evict = None
            for k, v in self._call_status.items():
                if v is not None and k not in self._wait_active:
                    evict = k
                    break
            if evict is not None:
                del self._call_status[evict]
                # a DEFERRED wait for an evicted id must still resolve:
                # record the high-water mark so MSG_WAIT can infer the
                # outcome (retirement is FIFO — an id at or below the
                # mark retired; its error, if any, is in _failed_calls)
                if evict > self._evicted_max:
                    self._evicted_max = evict
        self._call_cv.notify_all()

    # Direct value->member maps for the per-call hot path: EnumMeta
    # __call__ costs ~1us each and five enums ride every descriptor —
    # a dict hit is ~20x cheaper. Falls back to the constructor (KeyError
    # -> ValueError parity) for values outside the map.
    _OPS = dict(CCLOp._value2member_map_)
    _FUNCS = dict(ReduceFunc._value2member_map_)
    _ALGOS = dict(CollectiveAlgorithm._value2member_map_)

    def _execute(self, c: dict) -> int:
        try:
            scenario = self._OPS.get(c["scenario"])
            if scenario is None:  # zero-valued members are falsy: use `is`
                scenario = CCLOp(c["scenario"])
            if scenario == CCLOp.nop:
                return 0
            if scenario == CCLOp.config:
                return self._config(c)
            comm = self.comms.get(c["comm_id"])
            if comm is None:
                return int(ErrorCode.COMM_NOT_CONFIGURED)
            if self.dead_peers and any(r.global_rank in self.dead_peers
                                       for r in comm.ranks):
                # fail-fast containment (heartbeat membership): a
                # collective over a dead member can only burn its
                # deadline; comms excluding the peer run normally
                return int(ErrorCode.PEER_FAILED)
            if scenario == CCLOp.barrier:
                # rendezvous: 1-element fp32 allreduce on internal scratch;
                # every descriptor field that could change the data movement
                # is normalized so barrier semantics are dtype/flag-invariant
                f32 = P.DTYPE_CODES["float32"]
                c = dict(c, scenario=int(CCLOp.allreduce), count=1,
                         func=int(ReduceFunc.SUM), compression=0, stream=0,
                         algorithm=0, udtype=f32, cdtype=f32,
                         addr0=self._barrier_addr,
                         addr2=self._barrier_addr + 4)
                scenario = CCLOp.allreduce
            cfg = ArithConfig(P.code_dtype(c["udtype"]),
                              P.code_dtype(c["cdtype"]))
            if c["compression"] & int(Compression.BLOCK_SCALED):
                # block-scaled wire: rebuild the quantized config from
                # the descriptor's qblock byte (0 = default), the same
                # derivation the driver ran — segmentation and the
                # executor's quantize/dequant lanes key off quant_block
                import dataclasses as _dc

                from ..quant import DEFAULT_BLOCK
                cfg = _dc.replace(cfg, quant_block=(c.get("qblock")
                                                    or DEFAULT_BLOCK))
            if c["count"] * cfg.uncompressed_elem_bytes > P.MAX_CALL_BYTES:
                # sanity bound BEFORE expansion: a hostile count would
                # otherwise materialize count/segment move objects
                return int(ErrorCode.DMA_SIZE_ERROR)
            if scenario in (CCLOp.put, CCLOp.get):
                # one-sided: the RMA engine owns delivery + completion;
                # the FIFO call worker blocks until the transfer FINs
                # (the daemon call contract is synchronous retirement)
                handle = self._CallHandle(context=scenario.name)
                comp = Compression(c["compression"])
                if scenario == CCLOp.put:
                    local = c["addr0"]
                    local_c = bool(comp & Compression.OP0_COMPRESSED)
                    # addr2 is free on a put (no result buffer) and
                    # carries the notify token; 0 = none requested
                    notify = c["addr2"] or None
                else:
                    local = c["addr2"]
                    local_c = bool(comp & Compression.RES_COMPRESSED)
                    notify = None
                self.rma.start(
                    scenario, comm, c["root"], c["tag"], c["addr1"],
                    c["count"], cfg,
                    bool(comp & Compression.ETH_COMPRESSED),
                    local, handle,
                    tenant=self.comm_tenants.get(c["comm_id"], ""),
                    local_compressed=local_c, notify=notify)
                try:
                    handle.wait(self.timeout)
                    return 0
                except TimeoutError:
                    return int(ErrorCode.RECEIVE_TIMEOUT_ERROR)
                except Exception as exc:  # noqa: BLE001 — typed word out
                    word = getattr(exc, "error_word", 0)
                    return word or int(ErrorCode.INVALID_CALL)
            alg = c.get("algorithm", 0)
            func = self._FUNCS.get(c["func"])
            algorithm = self._ALGOS.get(alg)
            func = ReduceFunc(c["func"]) if func is None else func
            algorithm = (CollectiveAlgorithm(alg) if algorithm is None
                         else algorithm)
            compression = Compression(c["compression"])
            stream = StreamFlags(c["stream"])
            bases = (c["addr0"], c["addr1"], c["addr2"])
            # the one shared preparation path (plancache.cached_program):
            # resolves AUTO before keying, handles hit/miss/bypass (no
            # tuner daemon-side — descriptors normally arrive
            # pre-resolved; AUTO falls to the shared defaults)
            moves, skeleton, _state, _expand_us, _plan_us = \
                cached_program(
                    self.plan_cache, scenario=scenario, count=c["count"],
                    world_size=comm.size, local_rank=comm.local_rank,
                    arithcfg=cfg, max_segment_size=self.max_segment_size,
                    comm_id=c["comm_id"], comm_epoch=self.comm_epoch,
                    root_src_dst=c["root"], func=func, tag=c["tag"],
                    bases=bases, compression=compression, stream=stream,
                    algorithm=algorithm, counts=c.get("counts"),
                    streamed=(self.executor.window > 0
                              and self.executor.segment_stream),
                    tenant=(self.comm_tenants.get(c["comm_id"])
                            or f"comm-{c['comm_id']}"))
            return self.executor.execute(
                moves, cfg, comm, skeleton=skeleton,
                tenant=(self.comm_tenants.get(c["comm_id"])
                        or f"comm-{c['comm_id']}"),
                trace_tenant=self.comm_tenants.get(c["comm_id"], ""))
        except Exception:  # noqa: BLE001
            log.error("rank %d: call execution failed (scenario=%s "
                      "comm=%s)", self.rank, c.get("scenario"),
                      c.get("comm_id"), exc_info=True,
                      extra={"rank": self.rank})
            return int(ErrorCode.INVALID_CALL)

    # -- runtime config calls ----------------------------------------------
    def _config(self, c: dict) -> int:
        """ACCL_CONFIG through the call path (ccl_offload_control.c:
        1240-1283): subfunction in ``tag``, value in ``count`` (ms for
        timeout, bytes for segment size, StackType code for stack select).
        """
        try:
            fn = CfgFunc(c["tag"])
        except ValueError:
            return int(ErrorCode.INVALID_CALL)
        val = int(c["count"])
        if fn == CfgFunc.reset_periph:
            self._soft_reset()
            return 0
        if fn == CfgFunc.enable_pkt:
            self.pkt_enabled = True
            return 0
        if fn == CfgFunc.set_timeout:
            # same clamp as MSG_SET_TIMEOUT: feeds pool wait deadlines
            self.timeout = _sane_budget(val / 1000.0, configured=True)
            self.executor.timeout = self.timeout
            return 0
        if fn == CfgFunc.set_max_segment_size:
            if val > self.bufsize:  # segments must fit spare buffers
                return int(ErrorCode.DMA_SIZE_ERROR)
            self.max_segment_size = val
            return 0
        if fn == CfgFunc.open_port:
            return (0 if self.eth.listening
                    else int(ErrorCode.OPEN_PORT_NOT_SUCCEEDED))
        if fn == CfgFunc.open_con:
            return self.eth.connect_all()
        if fn == CfgFunc.close_con:
            self.eth.disconnect_all()
            return 0
        if fn == CfgFunc.set_stack_type:
            return self._set_stack({0: "tcp", 1: "udp",
                                    2: "shm"}.get(val))
        if fn == CfgFunc.start_profiling:
            self.profiling = True
            return 0
        if fn == CfgFunc.end_profiling:
            self.profiling = False
            return 0
        return int(ErrorCode.INVALID_CALL)

    def _bind_fabric(self, kind: str, port: int):
        """Bind a fresh fabric, retrying briefly (the kernel may take a
        moment to release the port); None if every attempt failed."""
        fabric_cls = _fabric_classes()[kind]
        for _ in range(50):
            try:
                return fabric_cls(self.rank, port, self._ingest)
            except OSError:
                time.sleep(0.05)
        return None

    def _set_stack(self, kind: str | None) -> int:
        """Runtime fabric swap (HOUSEKEEP_SET_STACK_TYPE parity,
        c:1270-1272). The swap is quiesced-only: in-flight eth traffic on
        the old fabric is lost, and every rank of the world must switch
        before new traffic flows."""
        if kind is None:
            return int(ErrorCode.INVALID_CALL)
        if kind == self.stack:
            return 0
        old_kind = self.stack
        port = self.port_base + self.world + self.rank
        self.eth.close()
        err = 0
        fab = self._bind_fabric(kind, port)
        if fab is None:
            # keep a working fabric: fall back to the old stack type
            # rather than leaving the daemon wired to a closed one
            err = int(ErrorCode.OPEN_PORT_NOT_SUCCEEDED)
            fab = self._bind_fabric(old_kind, port)
            if fab is None:  # port gone entirely; daemon is degraded
                return err
            kind = old_kind
        self.eth = fab
        self.stack = kind
        self.executor._send = self.eth.send
        self._wire_flush()  # coalescing hook follows the fabric swap
        self._wire_latch()  # so does the typed drop latch
        if self._unprobed:
            self._arm_presend()  # and the late caps re-probe
        for comm in self.comms.values():
            self.eth.learn_peers(
                [(r.global_rank, r.host, r.port) for r in comm.ranks],
                self.world)
        return err

    def _soft_reset(self):
        self.pool = RxBufferPool(len(self.pool.bufs), self.bufsize)
        self.pool.tenant_of = self.comm_tenants
        if self.rx_quota is not None:
            self.rx_quota.reset_usage()  # held buffers died with the pool
            self.pool.quota = self.rx_quota
        self.executor.pool = self.pool
        self.executor.reset_streams()
        self._wire_latch()  # the latch closure reads self.pool — rebound
        reset = getattr(self.eth, "reset_reliability", None)
        if reset is not None:
            # seqn spaces restart: channel state keyed on the old space
            # must go with them (every rank of the world resets, per the
            # soft-reset contract, so both ends clear)
            reset()
        # in-flight one-sided transfers die with the seqn spaces; window
        # registrations survive (configuration, like communicators)
        self.rma.reset()
        for comm in self.comms.values():
            for r in comm.ranks:
                r.inbound_seq = r.outbound_seq = 0

    # -- command server ----------------------------------------------------
    def serve_forever(self):
        """Accept driver connections (usually one) and serve requests."""
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # per-connection state for the WAIT_LAST sentinel: the id of the
        # last MSG_CALL this connection submitted
        conn_state = {"last_call_id": 0}
        # Buffered request parsing + coalesced replies: a pipelined
        # client batch ([pushes, CALL, WAIT, READ], sim.py _inline_fused)
        # lands in ONE recv, every frame is handled back to back, and
        # the replies leave in ONE sendall — instead of 2 recv syscalls
        # per frame (length + body) and a write + client wakeup per
        # reply. This is the daemon half of the isolated-call floor.
        # Frames/replies past _BIG_FRAME bypass the coalescing buffers:
        # big payloads recv directly into their destination and reply
        # via the scatter-gather send_frame (no extra full-size copies).
        _BIG = 1 << 20
        rbuf = bytearray()
        replies = bytearray()

        def flush():
            nonlocal replies
            if replies:
                conn.sendall(replies)
                replies = bytearray()
        try:
            while True:
                if len(rbuf) >= 4:
                    (length,) = struct.unpack_from("<I", rbuf)
                    if length > P.MAX_FRAME_LEN:
                        # earlier valid requests in the batch keep their
                        # replies even though this frame kills the conn
                        flush()
                        return
                    if length > _BIG and len(rbuf) < 4 + length:
                        # large frame (device-memory write): fill the
                        # remainder straight into the frame buffer with
                        # big reads — not 64K chunks through rbuf
                        body = bytearray(length)
                        have = len(rbuf) - 4
                        body[:have] = rbuf[4:]
                        del rbuf[:]
                        view = memoryview(body)[have:]
                        while view.nbytes:
                            n = conn.recv_into(view, min(view.nbytes,
                                                         1 << 20))
                            if not n:
                                return
                            view = view[n:]
                    elif len(rbuf) >= 4 + length:
                        body = bytes(rbuf[4:4 + length])
                        del rbuf[:4 + length]
                    else:
                        flush()
                        chunk = conn.recv(1 << 16)
                        if not chunk:
                            return
                        rbuf += chunk
                        continue
                    try:
                        reply = (self._handle(body, conn_state)
                                 if body else P.status_reply(
                                     int(ErrorCode.INVALID_CALL)))
                    except Exception:  # noqa: BLE001 — garbage frame
                        # must get an error reply, not a dead
                        # connection; log so genuine handler bugs
                        # stay diagnosable
                        log.exception(
                            "rank %d: request failed (kind=%s, "
                            "%d bytes)", self.rank,
                            body[0] if body else None, len(body),
                            extra={"rank": self.rank})
                        reply = P.status_reply(int(ErrorCode.INVALID_CALL))
                    if len(reply) > _BIG:
                        # big readback: scatter-gather send, zero-copy
                        flush()
                        P.send_frame(conn, reply)
                    else:
                        replies += struct.pack("<I", len(reply))
                        replies += reply
                    if body and body[0] == P.MSG_SHUTDOWN:
                        # teardown BEFORE the reply flush: the client's
                        # deinit blocks on this reply, which makes "the
                        # reply arrived" mean "resources are gone" — in
                        # particular the shm fabric's /dev/shm segments
                        # are unlinked before the client (often a test
                        # about to sweep /dev/shm, or an exiting
                        # process) proceeds
                        self.shutdown()
                        flush()
                        return
                    continue  # drain every buffered frame first
                flush()  # no complete frame left: flush the batch
                chunk = conn.recv(1 << 16)
                if not chunk:
                    return
                rbuf += chunk
        except (ConnectionError, OSError):
            return
        finally:
            # the accept loop still references the previous conn until its
            # next accept() returns, so without an explicit close a dropped
            # connection's fd would linger open (peers waiting on EOF hang)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, body: bytes, conn_state: dict | None = None) -> bytes:
        kind = body[0]
        if kind == P.MSG_PING:
            return P.status_reply(0)
        if kind == P.MSG_ALLOC:
            addr, nbytes = struct.unpack("<2Q", body[1:17])
            if nbytes > P.MAX_ALLOC_BYTES:  # bound hostile allocations
                return P.status_reply(int(ErrorCode.DMA_SIZE_ERROR))
            arr = np.zeros(nbytes, np.uint8)
            self._arrays[addr] = arr
            self.mem.register(addr, arr)
            return P.status_reply(0)
        if kind == P.MSG_FREE:
            (addr,) = struct.unpack("<Q", body[1:9])
            self.mem.deregister(addr)
            self._arrays.pop(addr, None)
            return P.status_reply(0)
        if kind == P.MSG_WRITE_MEM:
            (addr,) = struct.unpack("<Q", body[1:9])
            # offset view, not body[9:]: a device-memory write of a big
            # buffer must not memcpy the payload an extra time
            data = np.frombuffer(body, np.uint8, offset=9)
            self.mem.write(addr, data)
            return P.status_reply(0)
        if kind == P.MSG_READ_MEM:
            addr, nbytes = struct.unpack("<2Q", body[1:17])
            data = self.mem.read(addr, nbytes, np.dtype(np.uint8))
            return P.data_reply(data.tobytes())
        if kind == P.MSG_CONFIG_COMM:
            comm_id, local_rank, ranks, tenant = P.unpack_comm(body[1:])
            comm = Communicator(
                ranks=[Rank(host=h, port=p, global_rank=g)
                       for g, h, p in ranks],
                local_rank=local_rank, comm_id=comm_id)
            if comm_id in self.comms:
                # true RE-configuration: the comm's per-peer seqn spaces
                # restart at 0 — retransmission channel state keyed on
                # the old space must not dedup the new one away, and
                # stranded frames / latched error words of the old
                # membership (a grown-back comm's stale PEER_FAILED)
                # die with it
                reset = getattr(self.eth, "reset_comm", None)
                if reset is not None:
                    reset(comm_id)
                self.pool.purge_comm(comm_id)
            # join-handshake evidence restarts with the comm: a RE-grow
            # of the same membership (same comm id AND signature — e.g.
            # grow-back, shrink, grow-back again) must prove liveness
            # afresh, not inherit the previous handshake's heard-table.
            # A hello wiped by a late configure is recovered by the
            # sender's resend loop, and the sender's COMPLETION hello
            # covers the sender-already-finished case (_join_step).
            with self._join_cv:
                self._join_heard.pop(comm_id, None)
            self.comms[comm_id] = comm
            if tenant:
                # wire input: the label lands verbatim in Prometheus
                # label values and rejection-log lines — refuse unsafe
                # bytes (the client-side ACCL() validation does not
                # protect the daemon from other clients)
                from ..service import validate_tenant
                try:
                    validate_tenant(tenant)
                except ValueError:
                    log.warning(
                        "rank %d: ignoring invalid tenant label %r on "
                        "comm %d", self.rank, tenant, comm_id,
                        extra={"rank": self.rank})
                    tenant = ""
            if tenant:
                self.comm_tenants[comm_id] = tenant
            # reconfiguration invalidates compiled plans (membership /
            # rank numbering is baked into an expansion)
            self.comm_epoch += 1
            self.plan_cache.invalidate("comm")
            self.eth.learn_peers(ranks, self.world)
            self._maybe_pin_caps(ranks)
            return P.status_reply(0)
        if kind == P.MSG_REG_WINDOW:
            wid, addr, nbytes = struct.unpack("<IQQ", body[1:21])
            if nbytes == 0:
                self.windows.deregister(wid)
                return P.status_reply(0)
            try:
                # the whole window must lie inside registered device
                # memory, or the first inbound put would die on an
                # ingress thread (zero-copy view: validation, no copy)
                self.mem.read(addr, int(nbytes), np.dtype(np.uint8),
                              copy=False)
                self.windows.register(wid, addr, nbytes)
            except (KeyError, ValueError):
                return P.status_reply(int(ErrorCode.RMA_WINDOW_ERROR))
            return P.status_reply(0)
        if kind == P.MSG_RMA_NOTIFY:
            # drain put-with-notify completions: rank-local dequeue off
            # the engine's queue — the daemon-tier leg of the driver's
            # poll_notifications (no wire traffic, no collective)
            wid, mx = struct.unpack("<2I", body[1:9])
            recs = self.rma.notify.poll(wid, mx)
            return bytes([P.MSG_DATA]) + P.pack_notify_records(recs)
        if kind == P.MSG_JOIN:
            comm_id, sig, budget = P.unpack_join(body[1:])
            # short per-poll budget (MSG_STREAM_POP discipline): a long
            # blocking wait here would monopolize the command socket
            return self._join_step(comm_id, sig,
                                   min(max(0.0, budget), 0.5))
        if kind == P.MSG_SET_TIMEOUT:
            t = _sane_budget(struct.unpack("<d", body[1:9])[0],
                             configured=True)
            self.timeout = t
            self.executor.timeout = t
            return P.status_reply(0)
        if kind == P.MSG_SET_SEG:
            (nbytes,) = struct.unpack("<Q", body[1:9])
            if nbytes > self.bufsize:
                return P.status_reply(int(ErrorCode.DMA_SIZE_ERROR))
            self.max_segment_size = nbytes
            return P.status_reply(0)
        if kind == P.MSG_CALL:
            c = P.unpack_call(body[1:])
            with self._call_cv:
                call_id = self._next_call_id
                self._next_call_id += 1
                # WAITFOR_PREV resolves to the previous call THIS
                # connection submitted — not the globally-previous id,
                # which another connection's interleaved MSG_CALL could
                # claim and silently become the dependency
                if any(w == P.WAITFOR_PREV for w in c["waitfor"]):
                    prev = (conn_state["last_call_id"]
                            if conn_state is not None else call_id - 1)
                    c["waitfor"] = [prev if w == P.WAITFOR_PREV
                                    else w for w in c["waitfor"]]
                self._call_status[call_id] = None
                # Conn-thread fast path: retire the call right here when
                # FIFO order is provable (nothing queued or running) —
                # skipping two worker handoffs, and the client's
                # MSG_WAIT answers instantly. Blocking ops (recv waiting
                # on ingress, collectives rendezvousing peers) stall
                # only the MSG_CALL reply — semantics-preserving, since
                # the FIFO worker would have serialized every later call
                # of this rank behind them anyway; ingress and the wait
                # connection are served by other threads.
                inline = (not c["waitfor"] and not self._call_queue
                          and not self._executing)
                if inline:
                    self._executing += 1
                else:
                    # waitfor ordering: the single worker retires in
                    # FIFO order; waitfor ids reference earlier calls
                    self._call_queue.append((call_id, c))
                    self._call_cv.notify_all()
            if inline:
                t0 = time.perf_counter()
                err = self._execute(c)
                if self.profiling and c["scenario"] != int(CCLOp.config):
                    self.profiled_calls += 1
                    self.profile_time += time.perf_counter() - t0
                with self._call_cv:
                    self._executing -= 1
                    self._record_status(call_id, err)
            if conn_state is not None:
                conn_state["last_call_id"] = call_id
            return bytes([P.MSG_CALL_ID]) + struct.pack("<I", call_id)
        if kind == P.MSG_WAIT:
            (call_id,) = struct.unpack("<I", body[1:5])
            if call_id == P.WAIT_LAST and conn_state is not None:
                # "the last call THIS connection submitted" — lets the
                # client pipeline call+wait in one write (protocol.py)
                call_id = conn_state["last_call_id"]
            budget = _sane_budget(
                struct.unpack("<d", body[5:13])[0] if len(body) >= 13
                else self.timeout)
            import time as _time
            deadline = _time.monotonic() + budget
            with self._call_cv:
                self._wait_active[call_id] = \
                    self._wait_active.get(call_id, 0) + 1
                try:
                    while self._call_status.get(call_id) is None:
                        if (call_id not in self._call_status
                                and call_id <= self._evicted_max):
                            # evicted after retirement: FIFO means it DID
                            # retire; failures survive in _failed_calls —
                            # unless they TOO aged out of the bounded
                            # failure FIFO, in which case the outcome is
                            # unknowable and 0 would be a fabricated
                            # success
                            err = self._failed_calls.get(call_id)
                            if err is None:
                                err = (int(ErrorCode.CALL_OUTCOME_UNKNOWN)
                                       if call_id <=
                                       self._failed_evicted_max else 0)
                            return P.status_reply(err)
                        remaining = deadline - _time.monotonic()
                        if remaining <= 0:
                            return P.status_reply(P.STATUS_PENDING)
                        self._call_cv.wait(remaining)
                    err = self._call_status.pop(call_id)
                finally:
                    n = self._wait_active.get(call_id, 1) - 1
                    if n:
                        self._wait_active[call_id] = n
                    else:
                        self._wait_active.pop(call_id, None)
            return P.status_reply(err)
        if kind == P.MSG_GET_INFO:
            # base geometry + config-state extension (readable effect of
            # the runtime config calls; older clients parse a prefix)
            flags = ((1 if self.pkt_enabled else 0)
                     | (2 if self.profiling else 0))
            return P.data_reply(
                struct.pack("<Q3I", self.bufsize, len(self.pool.bufs),
                            self.world, self.rank)
                + struct.pack("<QIBBI", self.max_segment_size,
                              int(self.timeout * 1000), flags,
                              {"tcp": 0, "udp": 1,
                               "shm": 2}.get(self.stack, 0),
                              self.profiled_calls)
                # capability word (PR 11/13): this daemon answers retx
                # ACKs, serves one-sided RMA, and speaks payload
                # checksums. The native cclo_emud reports caps WITHOUT
                # these bits — which is what _maybe_pin_caps probes for
                # at configure time; replies from daemons predating the
                # field parse as caps=0. Csum caps track the LIVE eth
                # flag, not just the env var: a daemon with checksums
                # off (env-disabled or pinned) must not advertise them,
                # or peers would never pin and the wire would look
                # protected while this rank neither emits nor verifies.
                + struct.pack("<I",
                              P.CAP_RETX_ACK | P.CAP_RMA
                              | (P.csum_caps()
                                 if getattr(self.eth, "csum", False)
                                 else 0)
                              # CAP_SHM tracks the LIVE fabric: only a
                              # daemon whose eth IS the shm dataplane
                              # can serve ring-buffer peers
                              | (P.CAP_SHM
                                 if getattr(self.eth, "shm", False)
                                 else 0)))
        if kind == P.MSG_RESET:
            self._soft_reset()
            return P.status_reply(0)
        if kind == P.MSG_STREAM_PUSH:
            data = np.frombuffer(body[2:], P.code_dtype(body[1]))
            self.executor.push_stream(data)
            return P.status_reply(0)
        if kind == P.MSG_STREAM_POP:
            budget = _sane_budget(struct.unpack("<d", body[1:9])[0])
            count = struct.unpack("<Q", body[9:17])[0] if len(body) >= 17 \
                else 0
            try:
                out = self.executor.pop_stream_out(budget, count or None)
            except IndexError:
                return P.status_reply(P.STATUS_PENDING)
            return P.data_reply(bytes([P.dtype_code(out.dtype)])
                                + np.ascontiguousarray(out).tobytes())
        if kind == P.MSG_DUMP_RX:
            return P.data_reply(self.pool.describe().encode())
        if kind == P.MSG_SHUTDOWN:
            return P.status_reply(0)
        return P.status_reply(int(ErrorCode.INVALID_CALL))

    def shutdown(self):
        self._stop.set()
        self._server.close()
        self.rma.close()
        self.windows.close()
        self.eth.close()
        self.executor.close()


def _daemon_metrics_rows(d: "RankDaemon"):
    """Metrics collector for one rank daemon (polled at snapshot time):
    eth-fabric counters, rx-pool occupancy (+ high-water mark), executor
    pipeline counters of the last retired call, plan-cache counters."""
    labels = {"rank": d.rank, "tier": "daemon", "ctx": d.ctx_seq}
    for k, v in d.eth.stats.items():
        if k in ("dropped_queue_full", "fault_dropped",
                 "integrity_failed"):
            # already folded into fabric_dropped_total (per comm/src/dst)
            # by the UDP fabric's own collector / the direct fault-site
            # write (integrity failures: integrity_failed_total at the
            # landing verify) — re-yielding either as its own family
            # would show two events for one to any consumer summing it
            continue
        yield ("counter", f"fabric_{k}_total",
               dict(labels, fabric=d.stack), v)
    retx = getattr(d.eth, "retx", None)
    if retx is not None:
        for kind, name, lbl, v in retx.metrics_rows():
            yield (kind, name, dict(lbl, tier="daemon", ctx=d.ctx_seq), v)
    fabric_rows = getattr(d.eth, "metrics_rows", None)
    if fabric_rows is not None:
        # fabric-specific gauges (ShmFabric: per-link shm_link_up,
        # per-channel pinned arena bytes)
        for kind, name, lbl, v in fabric_rows():
            yield (kind, name, dict(lbl, tier="daemon", ctx=d.ctx_seq), v)
    # pool / executor / plan-cache rows: the same mapping the device
    # collector uses (tracing.health_rows), so the tiers cannot drift
    yield from health_rows(d, labels)
    for (peer, comm_id, tenant), n in list(d._rejections.items()):
        yield ("counter", "daemon_ingress_rejected_total",
               dict(labels, peer=peer, comm_id=comm_id, tenant=tenant), n)
    if d.rx_quota is not None:
        for tenant, n in d.rx_quota.in_use().items():
            yield ("gauge", "rx_pool_tenant_in_use",
                   dict(labels, tenant=tenant), n)
        for tenant, n in list(d.rx_quota.rejections.items()):
            # same family name as the device tier's RankService collector
            # (docs/OBSERVABILITY.md): one semantic counter, one key
            yield ("counter", "rx_pool_quota_rejected_total",
                   dict(labels, tenant=tenant), n)
    yield ("counter", "daemon_profiled_calls_total", labels,
           d.profiled_calls)


def spawn_world(world: int, port_base: int = 0, nbufs: int = 16,
                bufsize: int = 1 << 20, stack: str | None = None):
    """Spawn W in-process daemons on free ports (for tests); returns
    (daemons, port_base). Multi-process deployments run __main__ per rank."""
    # The contiguous cmd+eth port block lands in the ephemeral range, where
    # any outgoing connection on the host may hold a port — retry with a
    # fresh base on collision instead of failing the world.
    last_err: OSError | None = None
    for _ in range(20):
        base = port_base
        if base == 0:
            probe = socket.create_server(("127.0.0.1", 0))
            base = probe.getsockname()[1] + 101
            probe.close()
            if base + 2 * world >= 65536:  # block must fit in port space
                base -= 2 * world + 101
        daemons = []
        try:
            for r in range(world):
                d = RankDaemon(r, world, base, nbufs=nbufs, bufsize=bufsize,
                               host="127.0.0.1", stack=stack)
                daemons.append(d)
        except Exception as exc:
            for d in daemons:
                d.shutdown()
            if port_base != 0 or not isinstance(exc, OSError):
                raise
            last_err = exc
            continue
        for d in daemons:
            threading.Thread(target=d.serve_forever, daemon=True).start()
        return daemons, base
    raise OSError(f"no free port block after 20 attempts: {last_err}")


def main():
    ap = argparse.ArgumentParser(description="accl_tpu rank daemon")
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--port-base", type=int, default=45000)
    ap.add_argument("--nbufs", type=int, default=16)
    ap.add_argument("--bufsize", type=int, default=1 << 20)
    ap.add_argument("--stack", choices=["tcp", "udp", "shm"],
                    default=None,
                    help="eth fabric (default: $ACCL_TPU_FABRIC or tcp)")
    args = ap.parse_args()
    basic_config()  # rank-tagged stderr logging for standalone daemons
    daemon = RankDaemon(args.rank, args.world, args.port_base,
                        nbufs=args.nbufs, bufsize=args.bufsize,
                        stack=args.stack)
    print(f"rank {args.rank}/{args.world} serving on "
          f"cmd={args.port_base + args.rank} "
          f"eth={args.port_base + args.world + args.rank} "
          f"stack={daemon.stack}", flush=True)
    daemon.serve_forever()


if __name__ == "__main__":
    main()
