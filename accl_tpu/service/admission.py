"""Tenant-aware admission control: per-tenant queues, DWRR, depth bounds.

The multi-tenant collective service (ACCL+'s evolution of ACCL into a
shared offload service for many client applications) needs exactly one
new scheduling decision: *which queued program is admitted to the
streamed executor next*. Everything after admission is already isolated
— programs of distinct communicators share no lanes, RX match keys or
egress domains, so the executor's dependency machinery runs them
concurrently without further arbitration, and nothing is ever preempted
mid-program.

:class:`AdmissionController` implements that decision:

* one FIFO queue per *tenant* (a named group of communicators — by
  default each communicator is its own tenant);
* a deficit-weighted round-robin scheduler drains the queues: each
  scheduling round credits every backlogged tenant ``weight`` units of
  deficit and admits queued programs while the head's cost (bytes,
  normalized) fits — so configured weights become admitted-throughput
  shares under saturation, and a small-call tenant is never starved
  behind a bandwidth hog's multi-megabyte backlog;
* ``preempt`` tenants bypass the deficit round entirely (admitted the
  moment a slot is free — the ``preempt_admission`` knob: a
  latency-critical tenant overtakes at ADMISSION, never mid-program);
* per-tenant and aggregate depth bounds replace the single global
  ``ACCL_TPU_CALL_CHAIN_DEPTH``: every admitted program parks its
  not-yet-consumed inbound messages in the finite rx pool, so in-flight
  depth is a resource like any other — bounded per tenant;
* within one communicator the executor's ordering contract is preserved:
  a program is only admitted while its communicator has another program
  in flight when the caller chain-hinted it (the existing cross-call
  pipelining rules, now scoped per comm instead of globally).

Admission and retirement each run on small per-tenant worker threads
(admit + finish), so one tenant's barrier-heavy program can never
head-of-line-block another tenant's admission, and per-tenant handle
completion stays FIFO — the same contract the chain-finish thread gave
chained calls.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time

from ..constants import DEFAULT_TENANT_DEPTH

__all__ = ["ServiceConfig", "TenantSpec", "AdmissionController",
           "service_enabled", "tenant_label", "validate_tenant"]

import re

_TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


def validate_tenant(name: str) -> str:
    """Restrict explicit tenant labels to a safe charset: the label is
    spliced verbatim into CallRecord CSV rows, Prometheus label values,
    Perfetto track names and log lines — a comma/quote/newline would
    corrupt those encodings silently (the CSV round-trip would drop
    columns). Raises ValueError; returns the name for chaining."""
    if not _TENANT_RE.match(name):
        raise ValueError(
            f"invalid tenant label {name!r}: must match "
            "[A-Za-z0-9][A-Za-z0-9._-]{0,63} (it is embedded in CSV, "
            "Prometheus and trace encodings)")
    return name

# histogram bucket edges for queue-wait (microseconds): shared with the
# process registry's power-of-4 layout so collector rows merge natively
from ..tracing import MetricsRegistry as _MR

_HIST_BUCKETS = _MR._HIST_BUCKETS


def service_enabled() -> bool:
    """Process default for the service layer (``$ACCL_TPU_SERVICE``,
    on unless explicitly disabled)."""
    return os.environ.get("ACCL_TPU_SERVICE", "1").lower() not in (
        "0", "false", "off", "")


def tenant_label(comm_id: int, mapping: dict | None = None) -> str:
    """The tenant a communicator belongs to: the explicit grouping when
    one was configured (``ACCL(tenant=...)``), else the communicator is
    its own tenant."""
    if mapping:
        t = mapping.get(comm_id)
        if t:
            return t
    return f"comm-{comm_id}"


class TenantSpec:
    """Static per-tenant policy: scheduling weight, admission depth,
    preempt flag, resource reservations (rx-pool buffers / arena slots —
    consumed by the owner's :class:`~accl_tpu.service.quota.QuotaManager`
    construction, not by the controller itself)."""

    __slots__ = ("name", "weight", "depth", "preempt", "rx_buffers",
                 "arena_slots")

    def __init__(self, name: str, weight: float = 1.0,
                 depth: int | None = None, preempt: bool = False,
                 rx_buffers: int = 0, arena_slots: int = 0):
        self.name = validate_tenant(name)
        self.weight = max(0.001, float(weight))
        if depth is None:
            depth = int(os.environ.get("ACCL_TPU_TENANT_DEPTH",
                                       DEFAULT_TENANT_DEPTH))
        self.depth = max(1, int(depth))
        self.preempt = bool(preempt)
        self.rx_buffers = max(0, int(rx_buffers))
        self.arena_slots = max(0, int(arena_slots))


class ServiceConfig:
    """Configuration of one service instance (shared by every rank of a
    world — the specs are policy, the per-rank controllers/quotas are
    state). ``aggregate_depth`` bounds admitted programs across ALL
    tenants; 0 / None means "sum of the per-tenant bounds" (no extra
    constraint — a small aggregate with divergent per-rank admission
    orders can only be reconciled through recv-deadline aborts, so the
    default never creates that pressure)."""

    def __init__(self, enabled: bool | None = None,
                 aggregate_depth: int | None = None,
                 preempt_admission: bool | None = None):
        self.enabled = service_enabled() if enabled is None else bool(enabled)
        if aggregate_depth is None:
            aggregate_depth = int(os.environ.get(
                "ACCL_TPU_SERVICE_DEPTH", 0))
        self.aggregate_depth = max(0, int(aggregate_depth))
        if preempt_admission is None:
            preempt_admission = os.environ.get(
                "ACCL_TPU_PREEMPT_ADMISSION", "1").lower() not in (
                    "0", "false", "off", "")
        self.preempt_admission = bool(preempt_admission)
        self.tenants: dict[str, TenantSpec] = {}

    def tenant(self, name: str, **kw) -> TenantSpec:
        """Get-or-create the spec for ``name``; keyword arguments set
        policy fields on creation (weight/depth/preempt/rx_buffers/
        arena_slots)."""
        spec = self.tenants.get(name)
        if spec is None:
            spec = self.tenants[name] = TenantSpec(name, **kw)
        return spec

    def spec_of(self, name: str) -> TenantSpec:
        return self.tenants.get(name) or self.tenant(name)


class _Item:
    __slots__ = ("cost", "comm_id", "chain", "admit", "finish", "t_submit")

    def __init__(self, cost, comm_id, chain, admit, finish):
        self.cost = max(1.0, float(cost))
        self.comm_id = comm_id
        self.chain = bool(chain)
        self.admit = admit
        self.finish = finish
        self.t_submit = time.monotonic()


class _Tenant:
    __slots__ = ("spec", "queue", "deficit", "active", "admit_q", "fin_q",
                 "started", "admitted", "deferred", "wait_hist")

    def __init__(self, spec: TenantSpec):
        self.spec = spec
        self.queue: collections.deque[_Item] = collections.deque()
        self.deficit = 0.0
        self.active = 0
        self.admit_q: object = None   # queue.Queue, lazily with threads
        self.fin_q: object = None
        self.started = False
        self.admitted = 0
        self.deferred = 0
        # local queue-wait histogram in us: [count, sum, per-bucket n]
        # (folded into the registry by the owner's collector — a direct
        # registry observe per admission is the storm-shaped cost the
        # codebase keeps off hot paths)
        self.wait_hist = [0, 0.0, [0] * (len(_HIST_BUCKETS) + 1)]


class AdmissionController:
    """See module docstring. Thread-shape: ``submit`` is called by the
    owner (device call worker) in per-tenant program order; one scheduler
    thread grants admissions; per-tenant admit/finish worker pairs
    execute them. ``drain`` blocks until nothing is queued or in flight
    (the gate non-service executions and shutdown take)."""

    # bound on queued-but-not-admitted programs per tenant; submit blocks
    # past it (backpressure toward the submitting driver, like the old
    # chain-depth wait) rather than growing without limit
    MAX_QUEUE = int(os.environ.get("ACCL_TPU_SERVICE_QUEUE", 1024))
    _QUANTUM = 1.0  # deficit credit per backlogged tenant per round

    def __init__(self, config: ServiceConfig | None = None, name: str = ""):
        self.config = config or ServiceConfig()
        self.name = name
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._tenants: dict[str, _Tenant] = {}
        self._rr: list[str] = []          # round-robin order (first seen)
        # resumable DRR service state: the tenant currently being visited
        # and whether its visit is mid-flight (credited but bounds-blocked
        # before its deficit was spent — resumed WITHOUT re-crediting, so
        # a depth/aggregate stall never mints extra share and the service
        # order survives across scheduler wakeups; a per-pass restart
        # from _rr[0] would hand every freed aggregate slot to the first
        # tenant and starve the rest)
        self._rr_pos = 0
        self._visit_open = False
        self._comm_active: dict[int, int] = {}
        self._total_active = 0
        self._pending = 0                 # queued + active (drain gate)
        self._closed = False
        self._sched_started = False

    # -- submission --------------------------------------------------------
    def submit(self, tenant: str, cost: float, admit, finish, *,
               comm_id: int = 0, chain: bool = False,
               express_ok: bool = False):
        """Queue one program admission. ``admit()`` runs on the tenant's
        admit worker and returns an opaque program token; ``finish(prog,
        exc)`` runs on the tenant's finish worker (FIFO per tenant) with
        the token, or with the admit-time exception. Blocks only when the
        tenant's queue is at MAX_QUEUE (backpressure). ``express_ok``
        OPTS IN to the express grant (see below), which runs admit AND
        finish in the submitting thread — pass True only when the caller
        is synchronous anyway (a sync driver call) and ``admit()`` cannot
        park on a barrier; an async submitter must keep the non-blocking
        contract, and the DWRR queue discipline only governs what
        actually queues."""
        item = _Item(cost, comm_id, chain, admit, finish)
        express = False
        with self._cv:
            if self._closed:
                raise RuntimeError("admission controller closed")
            t = self._tenant_locked(tenant)
            while len(t.queue) >= self.MAX_QUEUE and not self._closed:
                self._cv.wait(0.5)
            if self._closed:
                raise RuntimeError("admission controller closed")
            if (express_ok and not t.queue and t.active == 0
                    and self._item_fits_locked(t, item, ())
                    and ((t.spec.preempt and self.config.preempt_admission)
                         or not any(tt.queue
                                    for tt in self._tenants.values()))):
                # EXPRESS admission, granted in the caller's thread: the
                # scheduler-thread and admit/finish-worker handoffs are
                # pure latency (each a cv/queue wake under load). Two
                # shapes: a PREEMPT tenant expresses past other tenants'
                # backlog (the knob's whole point), and ANY tenant
                # expresses while NO tenant has a QUEUED backlog —
                # granting then bypasses nobody (active programs already
                # hold their slots; there is nothing for the DWRR round
                # to arbitrate), and it is what lets N sync tenants run
                # wake-free in their own driver threads concurrently —
                # the concurrent-saturation throughput headline. With a
                # backlog anywhere, non-preempt admission must queue so
                # the weights decide. t.active == 0 keeps per-tenant
                # retirement FIFO: an inline admit must never overtake a
                # prior program still in the admit worker.
                express = True
                t.active += 1
                self._total_active += 1
                self._comm_active[item.comm_id] = \
                    self._comm_active.get(item.comm_id, 0) + 1
                t.admitted += 1
                self._observe_wait_locked(t, item)
                self._pending += 1
            elif (not t.queue
                  and not any(tt.queue for tt in self._tenants.values())
                  and self._item_fits_locked(t, item, ())):
                # immediate grant: no tenant has a backlog, so there is
                # nothing for the DWRR round to arbitrate and no one to
                # bypass — hand the item straight to the admit worker,
                # skipping the scheduler-thread wake the queued path
                # pays per call (measured: the grant handoffs were the
                # difference between the concurrent saturation run
                # beating and losing to the serialized baseline)
                t.active += 1
                self._total_active += 1
                self._comm_active[item.comm_id] = \
                    self._comm_active.get(item.comm_id, 0) + 1
                t.admitted += 1
                self._observe_wait_locked(t, item)
                self._ensure_workers_locked(t)
                self._pending += 1
                t.admit_q.put(item)
            else:
                if (t.queue or t.active >= t.spec.depth
                        or not self._agg_fits_locked()):
                    t.deferred += 1
                t.queue.append(item)
                self._pending += 1
                self._ensure_sched_locked()
                self._cv.notify_all()
        if express:
            # admit AND finish in the caller's thread: the admit-worker
            # and fin-worker handoffs are each an OS wake the latency
            # tenant would pay per call; t.active was 0, so no prior
            # retirement can be pending and per-tenant FIFO holds. The
            # caller blocks until the program drains — bounded by the
            # small call itself, which is the express contract.
            prog = exc = None
            try:
                prog = item.admit()
            except BaseException as e:  # noqa: BLE001 — same contract as
                exc = e                 # _admit_loop: surfaced via finish
            self._run_finish(t, item, prog, exc)

    def _tenant_locked(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            t = self._tenants[name] = _Tenant(self.config.spec_of(name))
            self._rr.append(name)
        return t

    def _agg_fits_locked(self) -> bool:
        agg = self.config.aggregate_depth
        return not agg or self._total_active < agg

    def _fits_locked(self, t: _Tenant) -> bool:
        if t.active >= t.spec.depth or not self._agg_fits_locked():
            return False
        head = t.queue[0]
        # per-comm ordering contract: only chain-hinted programs may be
        # admitted while their OWN communicator still has one in flight
        # (caller-asserted disjoint buffers); independent comms overlap
        # freely — they share no lanes, RX keys, or egress domains
        if not head.chain and self._comm_active.get(head.comm_id, 0):
            return False
        return True

    # -- scheduler ---------------------------------------------------------
    def _ensure_sched_locked(self):
        if not self._sched_started:
            self._sched_started = True
            threading.Thread(target=self._sched_loop, daemon=True,
                             name=f"svc-sched{self.name}").start()

    def _sched_loop(self):
        while True:
            with self._cv:
                while not self._closed and not self._grantable_locked():
                    self._cv.wait(0.2)
                if self._closed:
                    return
                grants = self._select_locked()
                for t, item in grants:
                    t.active += 1
                    self._total_active += 1
                    self._comm_active[item.comm_id] = \
                        self._comm_active.get(item.comm_id, 0) + 1
                    t.admitted += 1
                    self._observe_wait_locked(t, item)
                    self._ensure_workers_locked(t)
                    t.admit_q.put(item)
                if grants:
                    self._cv.notify_all()  # wake queue-full submitters

    def _grantable_locked(self) -> bool:
        return any(t.queue and self._fits_locked(t)
                   for t in self._tenants.values())

    def _select_locked(self) -> list[tuple[_Tenant, _Item]]:
        out: list[tuple[_Tenant, _Item]] = []
        # preempt pass: latency-critical tenants skip the deficit round
        # (grants collected in `out` count against bounds immediately via
        # _fits_effective, so a preempt burst cannot exceed its depth)
        if self.config.preempt_admission:
            for name in self._rr:
                t = self._tenants[name]
                while (t.spec.preempt and t.queue
                       and self._fits_effective(t, out)):
                    out.append((t, t.queue.popleft()))
        # Resumable deficit-weighted round robin over the backlog. One
        # VISIT credits a tenant weight*quantum and serves its queue
        # while the deficit covers the head cost. The two block reasons
        # are treated differently — the distinction is what makes the
        # weights hold under a scarce aggregate:
        # * tenant-LOCAL block (own depth cap, same-comm ordering): the
        #   rotation skips the tenant, creditless — a stalled tenant
        #   cannot bank share to burst when it unblocks;
        # * AGGREGATE block (the shared link every tenant contends on):
        #   the lap STOPS, and service resumes at this exact tenant —
        #   mid-visit without re-crediting — when a slot frees.
        # Restarting every pass from _rr[0] (or skipping agg-blocked
        # tenants creditless) would hand each freed aggregate slot to
        # whichever tenant the scan reaches first and starve the rest;
        # the resumable visit makes a 2:1 pair admit A,A,B,A,A,B...
        # Laps repeat while credit is still being minted, so a lone
        # tenant with an expensive head just takes a few laps to afford
        # it.
        n = len(self._rr)
        if n == 0:
            return out
        while True:
            any_credit = False
            for _ in range(n):
                self._rr_pos %= n
                t = self._tenants[self._rr[self._rr_pos]]
                if not t.queue:
                    t.deficit = 0.0
                    self._visit_open = False
                    self._rr_pos += 1
                    continue
                if not self._visit_open:
                    if self._tenant_blocked_locked(t, out):
                        self._rr_pos += 1
                        continue
                    if self._agg_blocked_locked(out):
                        return out  # resume HERE when a slot frees
                    t.deficit += self._QUANTUM * t.spec.weight
                    any_credit = True
                    self._visit_open = True
                while (t.queue and t.deficit >= t.queue[0].cost
                       and self._fits_effective(t, out)):
                    t.deficit -= t.queue[0].cost
                    out.append((t, t.queue.popleft()))
                if (t.queue and t.deficit >= t.queue[0].cost
                        and self._agg_blocked_locked(out)):
                    # affordable head frozen by the shared link: keep
                    # the visit open at this position
                    return out
                # visit complete: deficit spent, queue drained, or a
                # tenant-local block (deficit survives for the next
                # visit — DRR's carry when the head doesn't fit)
                if not t.queue:
                    t.deficit = 0.0
                self._visit_open = False
                self._rr_pos += 1
            if out or not any_credit:
                return out
            # A full lap minted credit but granted nothing: every
            # backlogged unblocked tenant is saving for an expensive
            # head. Iterating one quantum per lap would spin
            # O(head_cost/weight) lock-held laps (a 16 MiB program is
            # hundreds of cost units) — fast-forward the SAME schedule
            # by minting, for every such tenant at once, the number of
            # whole laps the nearest-affordable head still needs
            # (equal minting per lap keeps DRR's fairness: this is k
            # rounds at once, not a bypass).
            starving = [t for t in self._tenants.values()
                        if t.queue
                        and not self._tenant_blocked_locked(t, out)]
            if not starving:
                return out
            laps = min(
                max(1, math.ceil((t.queue[0].cost - t.deficit)
                                 / (self._QUANTUM * t.spec.weight)))
                for t in starving)
            if laps > 1:
                for t in starving:
                    t.deficit += (laps - 1) * self._QUANTUM * t.spec.weight

    def _tenant_blocked_locked(self, t: _Tenant, granted) -> bool:
        return self._item_blocked_locked(t, t.queue[0], granted)

    def _item_blocked_locked(self, t: _Tenant, item: _Item,
                             granted) -> bool:
        """Tenant-LOCAL admission block, counting this pass's not-yet-
        applied grants: own depth cap, or the per-comm ordering contract
        (only chain-hinted programs overlap their own communicator)."""
        mine = sum(1 for g, _ in granted if g is t)
        if t.active + mine >= t.spec.depth:
            return True
        if not item.chain and (
                self._comm_active.get(item.comm_id, 0)
                + sum(1 for _, it in granted
                      if it.comm_id == item.comm_id)):
            return True
        return False

    def _item_fits_locked(self, t: _Tenant, item: _Item, granted) -> bool:
        return (not self._item_blocked_locked(t, item, granted)
                and not self._agg_blocked_locked(granted))

    def _agg_blocked_locked(self, granted) -> bool:
        """The shared aggregate-depth link is exhausted (0 = unbounded)."""
        agg = self.config.aggregate_depth
        return bool(agg) and self._total_active + len(granted) >= agg

    def _fits_effective(self, t: _Tenant, granted) -> bool:
        """_fits_locked, counting this pass's not-yet-applied grants."""
        return (not self._tenant_blocked_locked(t, granted)
                and not self._agg_blocked_locked(granted))

    def _observe_wait_locked(self, t: _Tenant, item: _Item):
        us = (time.monotonic() - item.t_submit) * 1e6
        h = t.wait_hist
        h[0] += 1
        h[1] += us
        for i, edge in enumerate(_HIST_BUCKETS):
            if us <= edge:
                h[2][i] += 1
                break
        else:
            h[2][-1] += 1

    # -- per-tenant workers ------------------------------------------------
    def _ensure_workers_locked(self, t: _Tenant):
        if t.started:
            return
        t.started = True
        import queue as _q
        t.admit_q = _q.Queue()
        t.fin_q = _q.Queue()
        n = t.spec.name
        threading.Thread(target=self._admit_loop, args=(t,), daemon=True,
                         name=f"svc-admit-{n}{self.name}").start()
        threading.Thread(target=self._finish_loop, args=(t,), daemon=True,
                         name=f"svc-finish-{n}{self.name}").start()

    def _admit_loop(self, t: _Tenant):
        while True:
            item = t.admit_q.get()
            if item is None:
                t.fin_q.put(None)
                return
            prog = exc = None
            try:
                prog = item.admit()
            except BaseException as e:  # noqa: BLE001 — surfaced through
                exc = e                 # finish(prog=None, exc), never lost
            t.fin_q.put((item, prog, exc))

    def _finish_loop(self, t: _Tenant):
        while True:
            got = t.fin_q.get()
            if got is None:
                return
            item, prog, exc = got
            self._run_finish(t, item, prog, exc)

    def _run_finish(self, t: _Tenant, item: _Item, prog, exc):
        """Run one retirement callback and release its admission slots
        (shared by the per-tenant finish worker and the express path)."""
        try:
            item.finish(prog, exc)
        except BaseException:  # noqa: BLE001 — a raising finisher must
            pass               # not wedge the tenant's retirement FIFO
        finally:
            with self._cv:
                t.active -= 1
                self._total_active -= 1
                n = self._comm_active.get(item.comm_id, 1) - 1
                if n > 0:
                    self._comm_active[item.comm_id] = n
                else:
                    self._comm_active.pop(item.comm_id, None)
                self._pending -= 1
                self._cv.notify_all()

    # -- lifecycle / introspection -----------------------------------------
    def idle(self) -> bool:
        """True when nothing is queued or admitted (GIL-snapshot read)."""
        return self._pending == 0

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every submitted program retired. False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._pending:
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    self._cv.wait(remaining)
                else:
                    self._cv.wait(0.5)
        return True

    def drain_comm(self, comm_id: int, timeout: float | None = None) -> bool:
        """Block until nothing of ``comm_id`` is queued or admitted — the
        bounded wait a non-service call of ONE comm actually needs (the
        ordering contract is per comm; a global drain() would park it
        behind an unrelated tenant's endless storm)."""
        deadline = None if timeout is None else time.monotonic() + timeout

        def busy():
            return (self._comm_active.get(comm_id, 0)
                    or any(it.comm_id == comm_id
                           for t in self._tenants.values()
                           for it in t.queue))

        with self._cv:
            while busy():
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    self._cv.wait(remaining)
                else:
                    self._cv.wait(0.5)
        return True

    def close(self):
        # queued-but-never-granted items must still complete their
        # callers: run each finish with a closed error OUTSIDE the lock
        # (it completes handles and releases device-side accounting — a
        # caller parked in handle.wait() or a drain() would otherwise
        # hang on items that can no longer be admitted)
        dropped: list[tuple[_Tenant, _Item]] = []
        with self._cv:
            if self._closed:
                return
            self._closed = True
            for t in self._tenants.values():
                while t.queue:
                    dropped.append((t, t.queue.popleft()))
                if t.started:
                    t.admit_q.put(None)
            self._pending -= len(dropped)
            self._cv.notify_all()
        exc = RuntimeError("admission controller closed")
        for _t, item in dropped:
            try:
                item.finish(None, exc)
            except BaseException:  # noqa: BLE001 — shutdown best effort
                pass

    def stats(self) -> dict:
        with self._mu:
            return {name: {
                "weight": t.spec.weight, "depth": t.spec.depth,
                "preempt": t.spec.preempt, "queued": len(t.queue),
                "active": t.active, "admitted": t.admitted,
                "deferred": t.deferred,
                "queue_wait_us": {"count": t.wait_hist[0],
                                  "sum": t.wait_hist[1]},
            } for name, t in self._tenants.items()}

    def metrics_rows(self, labels: dict):
        """Registry-collector rows: per-tenant admission counters, queue
        depth gauges and the queue-wait histogram (polled at snapshot
        time only)."""
        with self._mu:
            tenants = [(name, t.admitted, t.deferred, len(t.queue),
                        t.active, [t.wait_hist[0], t.wait_hist[1],
                                   list(t.wait_hist[2])])
                       for name, t in self._tenants.items()]
        for name, admitted, deferred, queued, active, hist in tenants:
            lab = dict(labels, tenant=name)
            yield ("counter", "service_admitted_total", lab, admitted)
            yield ("counter", "service_deferred_total", lab, deferred)
            yield ("gauge", "service_queue_depth", lab, queued)
            yield ("gauge", "service_active_programs", lab, active)
            yield ("histogram", "service_queue_wait_us", lab, hist)
