"""Per-rank service instance: controller + quota managers + metrics.

One :class:`RankService` lives on each rank's execution backend (the emu
device today; the rank daemons wire the quota half directly — their
call path stays a single FIFO worker). It owns:

* the rank's :class:`~accl_tpu.service.admission.AdmissionController`
  (per-tenant queues, DWRR, depth bounds);
* the rank's resource :class:`~accl_tpu.service.quota.QuotaManager`\\ s,
  installed onto the rx buffer pool and the combine-scratch arena;
* the metrics collector folding per-tenant admission counters, queue-
  wait histograms and RX/arena occupancy into the process registry.
"""

from __future__ import annotations

from ..tracing import METRICS
from .admission import AdmissionController, ServiceConfig
from .quota import QuotaManager

__all__ = ["RankService"]


class RankService:
    def __init__(self, config: ServiceConfig, *, rank: int,
                 tenant_of: dict[int, str], pool=None, arena=None,
                 tier: str = "device"):
        self.config = config
        self.rank = rank
        self.tier = tier
        self.tenant_of = tenant_of  # live comm_id -> tenant mapping
        self.controller = AdmissionController(config, name=f"-r{rank}")
        self.rx_quota: QuotaManager | None = None
        self.arena_quota: QuotaManager | None = None
        if pool is not None:
            self.rx_quota = QuotaManager(
                len(pool.bufs),
                {n: s.rx_buffers for n, s in config.tenants.items()
                 if s.rx_buffers})
            self.wire_pool(pool)
        if arena is not None:
            self.arena_quota = QuotaManager(
                arena._slots,
                {n: s.arena_slots for n, s in config.tenants.items()
                 if s.arena_slots})
            arena.quota = self.arena_quota
        METRICS.register_collector(self, RankService._metrics_rows)

    def wire_pool(self, pool):
        """(Re)attach the rx quota to ``pool`` — soft reset builds a
        fresh pool, dropping every held buffer, so usage restarts from
        zero while cumulative rejection counts survive."""
        if self.rx_quota is None:
            return
        self.rx_quota.reset_usage()
        pool.quota = self.rx_quota
        pool.tenant_of = self.tenant_of

    def _metrics_rows(self):
        labels = {"rank": self.rank, "tier": self.tier}
        yield from self.controller.metrics_rows(labels)
        for qm, family in ((self.rx_quota, "rx_pool"),
                           (self.arena_quota, "arena")):
            if qm is None:
                continue
            for tenant, n in qm.in_use().items():
                yield ("gauge", f"{family}_tenant_in_use",
                       dict(labels, tenant=tenant), n)
            for tenant, n in list(qm.rejections.items()):
                yield ("counter", f"{family}_quota_rejected_total",
                       dict(labels, tenant=tenant), n)

    def close(self):
        self.controller.close()
