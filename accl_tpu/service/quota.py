"""Per-tenant resource quotas: reserved shares + a common overflow pool.

One :class:`QuotaManager` accounts for one finite rank-local resource —
rx-pool spare buffers, combine-scratch arena slots — split into
per-tenant *reservations* (guaranteed: nobody else can take them) and a
shared *overflow* pool (whatever the reservations don't cover, first
come first served). A tenant may always use up to its reservation; past
it, units come from overflow while any remain. This is what keeps one
communicator's 16 MiB storm from starving another communicator's recv
matching (ACCL+'s multi-application isolation, ROADMAP item 3): the
storm can exhaust overflow, never a victim's reserved buffers.

The manager is deliberately tiny and lock-local: acquire/release sit on
the eager-ingress path, so one small mutex and two dict updates is the
whole cost. Rejections (a unit finally *dropped* because the quota never
freed within the ingest timeout) are counted per tenant for the metrics
collector; transient denials that backpressure resolves are not failures
and are not counted.
"""

from __future__ import annotations

import threading

__all__ = ["QuotaManager", "parse_reservations"]


def parse_reservations(spec: str) -> dict[str, int]:
    """Parse an env-style reservation spec: ``"tenantA:4,tenantB:2"`` ->
    ``{"tenantA": 4, "tenantB": 2}`` (used by the rank daemons, which
    have no in-process ServiceConfig to read)."""
    out: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, n = part.rpartition(":")
        out[name.strip()] = int(n)
    return out


class QuotaManager:
    """Reserved-plus-overflow accounting for ``total`` resource units.

    Reservations exceeding ``total`` are scaled down proportionally (a
    misconfigured sum must degrade to smaller guarantees, not negative
    overflow). Tenants without a reservation draw purely from overflow.
    """

    def __init__(self, total: int, reservations: dict[str, int] | None = None):
        self.total = int(total)
        reservations = dict(reservations or {})
        reserved_sum = sum(max(0, n) for n in reservations.values())
        if reserved_sum > self.total and reserved_sum:
            scale = self.total / reserved_sum
            reservations = {t: int(n * scale)
                            for t, n in reservations.items()}
            reserved_sum = sum(reservations.values())
        self.reserved = {t: max(0, int(n)) for t, n in reservations.items()}
        self.overflow = self.total - sum(self.reserved.values())
        self._mu = threading.Lock()
        self._used: dict[str, int] = {}
        self._overflow_used = 0
        self.rejections: dict[str, int] = {}

    def try_acquire(self, tenant: str) -> bool:
        """Claim one unit for ``tenant``; False = quota denied (the
        caller backpressures or, on timeout, drops + notes a rejection).
        """
        with self._mu:
            used = self._used.get(tenant, 0)
            if used < self.reserved.get(tenant, 0):
                self._used[tenant] = used + 1
                return True
            if self._overflow_used < self.overflow:
                self._overflow_used += 1
                self._used[tenant] = used + 1
                return True
            return False

    def release(self, tenant: str):
        with self._mu:
            used = self._used.get(tenant, 0)
            if used <= 0:
                return  # unbalanced release: tolerate, never go negative
            # any usage above the reservation came from overflow — return
            # it there first so another tenant's burst can claim it
            if used > self.reserved.get(tenant, 0):
                self._overflow_used -= 1
            if used == 1:
                self._used.pop(tenant, None)
            else:
                self._used[tenant] = used - 1

    def reset_usage(self):
        """Zero the live usage accounting (the owner's pool was rebuilt
        by a soft reset, dropping every held unit); cumulative rejection
        counts survive — they are history, not state."""
        with self._mu:
            self._used.clear()
            self._overflow_used = 0

    def note_rejection(self, tenant: str):
        """A unit was finally dropped on this tenant's quota (ingest
        timeout expired with the quota still exhausted)."""
        with self._mu:
            self.rejections[tenant] = self.rejections.get(tenant, 0) + 1

    def in_use(self) -> dict[str, int]:
        with self._mu:
            return dict(self._used)

    def stats(self) -> dict:
        with self._mu:
            return {"total": self.total, "overflow": self.overflow,
                    "overflow_used": self._overflow_used,
                    "reserved": dict(self.reserved),
                    "in_use": dict(self._used),
                    "rejections": dict(self.rejections)}
