"""Multi-tenant collective service: admission, QoS scheduling, quotas.

The service layer (ROADMAP item 3, after ACCL+'s evolution of ACCL into
a shared collective service for many client applications) sits in front
of the streamed executor: programs from *independent* communicators are
admitted concurrently (they share no lanes, RX match keys or egress
domains — the executor's dependency machinery already isolates them),
per-tenant queues are drained by a deficit-weighted round-robin
scheduler, and rank-local resources (rx-pool spare buffers, combine-
scratch arena slots) carry per-tenant reservations with a shared
overflow pool. See docs/ARCHITECTURE.md "The service layer".

``$ACCL_TPU_SERVICE=0`` disables the layer process-wide (every call
takes the legacy serialized path).
"""

from .admission import (AdmissionController, ServiceConfig, TenantSpec,
                        service_enabled, tenant_label, validate_tenant)
from .quota import QuotaManager, parse_reservations
from .rank import RankService

__all__ = [
    "AdmissionController", "RankService", "ServiceConfig", "TenantSpec",
    "QuotaManager", "parse_reservations", "service_enabled",
    "tenant_label", "validate_tenant",
]
