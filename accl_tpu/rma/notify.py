"""Put-with-notify completion queue — the serving control plane's
"which requests' KV arrived" primitive.

A put carrying a notify token (``ACCL.put(..., notify=token)``) makes
the TARGET enqueue one :class:`NotifyRecord` on its rank-local queue
when the transfer lands in the window — or a typed-error record when it
fails there (unknown window, out-of-range offset). Discovery is then ONE
local dequeue (:meth:`NotifyQueue.poll`): no collective, no per-buffer
scan, no matching receive. The record rides the engine's existing
DONE/FIN lane — the notify token travels once in the opening RTS/EAGER
frame, is kept with the target's receive state, and the enqueue happens
exactly at the done-memo write (``engine._memo_done``), which is the
engine's exactly-once boundary: duplicate RTS/DONE/EAGER frames after
completion re-FIN from the memo and never re-enqueue, so a lost-FIN
retry storm cannot produce duplicate completions.

Bounded: past ``cap`` records the OLDEST is dropped and counted
(``notify_dropped_total``) — a serving loop that stops polling must
degrade into lost notifications, not unbounded memory; the block
manager's ref-counting state machine treats a lost notification like a
lost request (timeout + retry), never as silent corruption.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque

DEFAULT_NOTIFY_CAP = 4096
ANY_WINDOW = 0xFFFFFFFF


@dataclasses.dataclass(frozen=True)
class NotifyRecord:
    """One completed (or typed-failed) inbound put-with-notify."""

    token: int    # initiator-chosen request token (u64)
    window: int   # target window id the put addressed
    src: int      # initiator's global rank
    err: int      # 0 = landed clean; typed error word otherwise
    offset: int   # byte offset inside the window
    nbytes: int   # uncompressed bytes landed (0 on error)


class NotifyQueue:
    """Per-rank completion queue, partitioned by window id. ``push`` runs
    on ingress threads (the engine's DONE/EAGER handlers); ``poll`` on
    the application's serving loop — one lock, no allocation on the
    empty-poll fast path."""

    def __init__(self, cap: int = DEFAULT_NOTIFY_CAP):
        self._mu = threading.Lock()
        self._qs: dict[int, deque] = {}
        self.cap = int(cap)
        self.dropped = 0
        self.enqueued = 0
        self.polled = 0

    def push(self, rec: NotifyRecord):
        with self._mu:
            q = self._qs.get(rec.window)
            if q is None:
                q = self._qs[rec.window] = deque()
            if len(q) >= self.cap:
                q.popleft()
                self.dropped += 1
            q.append(rec)
            self.enqueued += 1

    def poll(self, window: int = ANY_WINDOW,
             max_records: int = 64) -> list[NotifyRecord]:
        """Dequeue up to ``max_records`` completions for ``window``
        (ANY_WINDOW drains round-robin across windows). Purely local —
        the no-collective property the serving gate pins."""
        out: list[NotifyRecord] = []
        n = max(0, int(max_records))
        with self._mu:
            if window != ANY_WINDOW:
                q = self._qs.get(int(window))
                while q and len(out) < n:
                    out.append(q.popleft())
            else:
                # round-robin so one hot window cannot starve the rest
                live = [q for q in self._qs.values() if q]
                while live and len(out) < n:
                    nxt = []
                    for q in live:
                        if q and len(out) < n:
                            out.append(q.popleft())
                        if q:
                            nxt.append(q)
                    live = nxt
            self.polled += len(out)
        return out

    def pending(self, window: int = ANY_WINDOW) -> int:
        with self._mu:
            if window != ANY_WINDOW:
                q = self._qs.get(int(window))
                return len(q) if q else 0
            return sum(len(q) for q in self._qs.values())

    def clear(self):
        with self._mu:
            self._qs.clear()
