"""One-sided RMA: registered memory windows + put/get with rendezvous.

The missing primitive for the inference-serving dataplane (ROADMAP item
5, ACCL+'s "collective engine for distributed applications" end-state):
a prefill rank streams multi-MiB KV-cache blocks into a decode rank's
registered window WITHOUT posting matching receives and — the tested
invariant — without consuming the rx-buffer pool that the decode rank's
latency-critical collectives depend on. See
:mod:`accl_tpu.rma.engine` for the delivery paths and reliability story,
:mod:`accl_tpu.rma.plan` for the (pure, lint-replayed) segmentation, and
docs/ARCHITECTURE.md "One-sided operations".
"""

from .engine import RmaEngine
from .notify import ANY_WINDOW, NotifyQueue, NotifyRecord
from .plan import (EAGER, RENDEZVOUS, TransferPlan, eager_max_from_env,
                   plan_transfer, segment_bounds)
from .window import Window, WindowRegistry

__all__ = [
    "RmaEngine", "Window", "WindowRegistry", "TransferPlan",
    "plan_transfer", "segment_bounds", "eager_max_from_env",
    "EAGER", "RENDEZVOUS", "NotifyQueue", "NotifyRecord", "ANY_WINDOW",
]
