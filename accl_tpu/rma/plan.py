"""Pure transfer planning for one-sided put/get.

The delivery-path decision (eager vs rendezvous) and the rendezvous
segmentation live here as pure functions of the call shape, so the
initiator's emission plan and the target's landing arithmetic can never
disagree: the RTS/GET control frame carries ``(count, nsegs)`` and BOTH
sides derive segment boundaries from them alone
(:func:`segment_bounds`). ``scripts/check_blocking.py`` check 6 replays a
corpus of these plans — full coverage, disjointness, in-order segment
indices, sender/receiver boundary agreement — the same way it replays
move programs.
"""

from __future__ import annotations

import dataclasses
import os

from ..constants import DEFAULT_RMA_EAGER_MAX

EAGER = "eager"
RENDEZVOUS = "rendezvous"


def eager_max_from_env() -> int:
    return max(0, int(os.environ.get("ACCL_TPU_RMA_EAGER_MAX",
                                     DEFAULT_RMA_EAGER_MAX)))


@dataclasses.dataclass(frozen=True)
class TransferPlan:
    """One put/get transfer, fully determined by the call shape."""

    kind: str                               # EAGER | RENDEZVOUS
    count: int                              # elements
    elem_bytes: int                         # in-window element size
    wire_elem_bytes: int                    # on-the-wire element size
    segments: tuple[tuple[int, int], ...]   # (elem_off, elems) per segment

    @property
    def nsegs(self) -> int:
        return len(self.segments)

    @property
    def nbytes(self) -> int:
        return self.count * self.elem_bytes

    @property
    def wire_bytes(self) -> int:
        return self.count * self.wire_elem_bytes


def segment_bounds(count: int, nsegs: int) -> tuple[tuple[int, int], ...]:
    """Uniform segmentation shared by initiator and target: given only
    the RTS/GET fields ``(count, nsegs)``, segment ``i`` covers elements
    ``[i*seg, min(count, (i+1)*seg))`` with ``seg = ceil(count/nsegs)``.
    The one copy of the landing arithmetic — a target must never guess
    boundaries from its own segment-size config, which may differ from
    the initiator's."""
    if count <= 0 or nsegs <= 0:
        return ()
    seg = -(-count // nsegs)
    out = []
    off = 0
    while off < count:
        n = min(seg, count - off)
        out.append((off, n))
        off += n
    return tuple(out)


def plan_transfer(count: int, elem_bytes: int, wire_elem_bytes: int,
                  max_segment_size: int,
                  eager_max: int | None = None) -> TransferPlan:
    """Plan one transfer: eager when the whole wire payload fits the
    eager threshold (one frame, rides the rx pool), rendezvous otherwise
    (segments of at most ``max_segment_size`` wire bytes, streamed
    directly into the window)."""
    if eager_max is None:
        eager_max = eager_max_from_env()
    wire_bytes = count * wire_elem_bytes
    if wire_bytes <= eager_max:
        return TransferPlan(EAGER, count, elem_bytes, wire_elem_bytes,
                            ((0, count),) if count else ())
    seg_elems = max(1, max_segment_size // max(1, wire_elem_bytes))
    nsegs = -(-count // seg_elems)
    return TransferPlan(RENDEZVOUS, count, elem_bytes, wire_elem_bytes,
                        segment_bounds(count, nsegs))
