"""Registered memory windows — the address namespace of one-sided ops.

A window is a ``(window_id -> [addr, addr+nbytes))`` registration on ONE
rank; a peer's put/get names ``(target_rank, window_id, byte offset)``
and the target resolves it locally. Ids are exchanged at configure time
by the application — the driver's :meth:`~accl_tpu.accl.ACCL.
register_window` hands them out from a per-driver counter, so symmetric
registration order yields agreeing ids without a handshake (the same
determinism contract ``split_communicator`` uses for comm ids).
"""

from __future__ import annotations

import dataclasses
import threading

from ..constants import ACCLError, ErrorCode


@dataclasses.dataclass(frozen=True)
class Window:
    wid: int
    addr: int
    nbytes: int


class WindowRegistry:
    """Per-rank window table. Registration happens at configure time from
    the host; resolution happens on ingress threads for every RTS/GET —
    a lock-guarded dict keeps both safe."""

    def __init__(self):
        self._mu = threading.Lock()
        self._windows: dict[int, Window] = {}

    def register(self, wid: int, addr: int, nbytes: int):
        if nbytes <= 0:
            raise ValueError(f"window {wid}: nbytes must be positive, "
                             f"got {nbytes}")
        with self._mu:
            self._windows[int(wid)] = Window(int(wid), int(addr),
                                             int(nbytes))

    def deregister(self, wid: int):
        with self._mu:
            self._windows.pop(int(wid), None)

    def get(self, wid: int) -> Window | None:
        with self._mu:
            return self._windows.get(int(wid))

    def resolve(self, wid: int, offset: int, nbytes: int) -> int:
        """Byte address of ``[offset, offset+nbytes)`` inside window
        ``wid``; raises the typed window error when the id is unknown or
        the range falls outside the registration — the failure an RTS/GET
        handler FINs back to the initiator."""
        with self._mu:
            w = self._windows.get(int(wid))
        if w is None:
            raise ACCLError(int(ErrorCode.RMA_WINDOW_ERROR),
                            f"window {wid} not registered")
        if offset < 0 or offset + nbytes > w.nbytes:
            raise ACCLError(
                int(ErrorCode.RMA_WINDOW_ERROR),
                f"range [{offset}, +{nbytes}) outside window {wid} "
                f"({w.nbytes} B)")
        return w.addr + int(offset)

    def __len__(self) -> int:
        with self._mu:
            return len(self._windows)
