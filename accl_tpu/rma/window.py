"""Registered memory windows — the address namespace of one-sided ops.

A window is a ``(window_id -> [addr, addr+nbytes))`` registration on ONE
rank; a peer's put/get names ``(target_rank, window_id, byte offset)``
and the target resolves it locally. Ids are exchanged at configure time
by the application — the driver's :meth:`~accl_tpu.accl.ACCL.
register_window` hands them out from a per-driver counter, so symmetric
registration order yields agreeing ids without a handshake (the same
determinism contract ``split_communicator`` uses for comm ids).
"""

from __future__ import annotations

import dataclasses
import threading
import weakref

from ..constants import ACCLError, ErrorCode

# every live registry, weakly (dies with its world): the conftest
# window-leak sweep walks this after each test to assert the repo-wide
# convention that a deregistered (closed) world leaves an EMPTY registry
# — the /dev/shm-sweep convention applied to the RMA address namespace
_LIVE: "weakref.WeakSet[WindowRegistry]" = weakref.WeakSet()


def sweep_leaked() -> list[str]:
    """Find (and clean) window registrations that outlived their world:
    any CLOSED registry still holding entries — a use-after-deinit
    register, or a close path that forgot to purge. Returns one
    description per leaking registry; leftovers are cleared so one
    test's leak cannot cascade into the next test's failure."""
    leaked: list[str] = []
    for reg in list(_LIVE):
        n = len(reg)
        if reg.closed and n:
            leaked.append(f"{reg.owner or 'registry'}: {n} window(s) "
                          f"registered after close")
            with reg._mu:
                reg._windows.clear()
    return leaked


@dataclasses.dataclass(frozen=True)
class Window:
    wid: int
    addr: int
    nbytes: int


class WindowRegistry:
    """Per-rank window table. Registration happens at configure time from
    the host; resolution happens on ingress threads for every RTS/GET —
    a lock-guarded dict keeps both safe. :meth:`close` (device deinit)
    marks the registry dead and purges every registration: stale windows
    on a torn-down rank would otherwise keep accepting peer puts into
    memory the application has moved on from."""

    def __init__(self, owner: str = ""):
        self._mu = threading.Lock()
        self._windows: dict[int, Window] = {}
        self.owner = owner
        self.closed = False
        _LIVE.add(self)

    def register(self, wid: int, addr: int, nbytes: int):
        if nbytes <= 0:
            raise ValueError(f"window {wid}: nbytes must be positive, "
                             f"got {nbytes}")
        with self._mu:
            self._windows[int(wid)] = Window(int(wid), int(addr),
                                             int(nbytes))

    def deregister(self, wid: int):
        with self._mu:
            self._windows.pop(int(wid), None)

    def close(self):
        """Tear down at device deinit: purge every registration and mark
        the registry dead. Registrations that appear AFTER close are the
        leak class the conftest sweep (:func:`sweep_leaked`) reports."""
        with self._mu:
            self._windows.clear()
            self.closed = True

    def get(self, wid: int) -> Window | None:
        with self._mu:
            return self._windows.get(int(wid))

    def resolve(self, wid: int, offset: int, nbytes: int) -> int:
        """Byte address of ``[offset, offset+nbytes)`` inside window
        ``wid``; raises the typed window error when the id is unknown or
        the range falls outside the registration — the failure an RTS/GET
        handler FINs back to the initiator."""
        with self._mu:
            w = self._windows.get(int(wid))
        if w is None:
            raise ACCLError(int(ErrorCode.RMA_WINDOW_ERROR),
                            f"window {wid} not registered")
        if offset < 0 or offset + nbytes > w.nbytes:
            raise ACCLError(
                int(ErrorCode.RMA_WINDOW_ERROR),
                f"range [{offset}, +{nbytes}) outside window {wid} "
                f"({w.nbytes} B)")
        return w.addr + int(offset)

    def __len__(self) -> int:
        with self._mu:
            return len(self._windows)
