"""The one-sided RMA engine: put/get with eager and rendezvous delivery.

One engine per rank, attached to whatever fabric that rank's tier speaks
(the in-process LocalFabric, the daemon's TCP/UDP eth fabrics — the
engine only needs ``send_fn(env, payload)`` and an ingress hook). Two
delivery paths, chosen per transfer by :func:`~accl_tpu.rma.plan.
plan_transfer`:

* **eager** (small wire payloads): ONE control+payload frame
  (``RMA_EAGER``). The target routes the payload through its rx-buffer
  pool exactly like an eager-ingress collective message — claiming a
  spare buffer, charging the comm's TENANT quota (accl_tpu/service),
  honoring the oversize latch — before landing it in the window. Small
  puts therefore obey the same backpressure/quota regime as everything
  else.

* **rendezvous** (large payloads): ``RTS -> CTS`` handshake on the
  ``RMA_STRM`` control lane, then payload segments streamed on
  ``RMA_DATA_STRM`` directly into the registered window. **No segment
  ever touches the rx pool** — the tested invariant: a multi-MiB
  KV-cache push must not consume the spare buffers the target's
  latency-critical collectives depend on.

Reliability is the engine's own (the PR-9 retransmission layer
deliberately ignores ``strm >= 2`` control lanes): initiator-driven
control retries with exponential backoff (RTS awaiting CTS, DONE
awaiting FIN, GET awaiting data), receiver-side segment dedup by index,
and selective ``NACK``-driven resend of exactly the missing segments
after ``DONE`` — so a seeded :class:`~accl_tpu.chaos.FaultPlan`
dropping/duplicating/delaying any control frame or a mid-stream segment
still converges to a bit-identical landing. Completion surfaces as the
ordinary :class:`~accl_tpu.call.CallHandle` the driver hands out, so
puts chain behind compute (``waitfor=``), driver-level retry policies
apply, and per-tenant attribution rides CallRecords/metrics/traces
unchanged.
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time

import numpy as np

from ..call import CallHandle
from ..constants import (ACCLError, CCLOp, DEFAULT_RMA_EAGER_MAX,
                         DEFAULT_RMA_MAX_TRIES, DEFAULT_RMA_RTO_S,
                         ErrorCode)
from ..emulator import protocol as P
from ..emulator.fabric import Envelope
from ..log import get_logger
from ..tracing import METRICS, TRACE
from .notify import NotifyQueue, NotifyRecord
from .plan import EAGER, eager_max_from_env, plan_transfer, segment_bounds
from .window import WindowRegistry

log = get_logger(__name__)

# synthetic rx-pool seqn space for eager frames: far above any collective
# channel's dense per-peer counters, and unique per transfer (xfer ids
# carry the initiator's rank bits). Never crosses the fabric — it is only
# the pool-matching key on the target.
_POOL_SEQ_BASE = 0x80000000

_DONE_MEMO_CAP = 1024


class _Tx:
    """Initiator-side transfer state (one put or get)."""

    __slots__ = ("kind", "xfer", "comm", "comm_id", "dst", "window",
                 "offset", "count", "u_dtype", "w_dtype", "l_dtype",
                 "eth_c", "addr", "plan", "handle", "tenant", "phase",
                 "tries", "deadline", "got", "done_seen", "t0", "notify")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class _Rx:
    """Target-side state of one inbound rendezvous put."""

    __slots__ = ("base", "count", "u_dtype", "w_dtype", "eth_c", "nsegs",
                 "bounds", "got", "comm_id", "tenant", "expires",
                 "notify", "window", "offset")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class _Srv:
    """Target-side state of one outbound get serve (kept until FIN or
    TTL so a NACK can re-read exactly the missing segments from the
    window)."""

    __slots__ = ("base", "count", "u_dtype", "w_dtype", "eth_c", "nsegs",
                 "bounds", "comm_id", "dst", "tenant", "expires")

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class RmaEngine:
    """Per-rank one-sided engine. ``pool_fn``/``seg_fn``/``timeout_fn``
    are late-bound getters (soft reset swaps the pool object; config
    calls change segment size and timeout); ``comm_of`` maps comm_id ->
    Communicator; ``tenant_of`` maps comm_id -> tenant label for
    attribution."""

    def __init__(self, rank: int, mem, windows: WindowRegistry, send_fn, *,
                 pool_fn, comm_of, tenant_of=None, timeout_fn=None,
                 seg_fn=None, eager_max: int | None = None,
                 rto_s: float = DEFAULT_RMA_RTO_S,
                 max_tries: int = DEFAULT_RMA_MAX_TRIES, tier: str = "emu",
                 csum_fn=None, tuner_fn=None):
        self.rank = rank
        self.mem = mem
        self.windows = windows
        self._send = send_fn
        # live checksum flag of the owning fabric (late-bound: configure
        # time can PIN checksums off against a variant-mismatched peer,
        # and a pinned/disabled rank must stop VERIFYING too — its own
        # CRC variant may be the very thing that disagrees, and the
        # engine's NACK re-fetch would re-reject the same healthy frame
        # forever). Mirrors daemon._verify_frame's ``enabled`` gate.
        self.csum_fn = csum_fn or (lambda: True)
        self.pool_fn = pool_fn
        self.comm_of = comm_of
        self.tenant_of = tenant_of or (lambda cid: f"comm-{cid}")
        self.timeout_fn = timeout_fn or (lambda: 30.0)
        self.seg_fn = seg_fn or (lambda: 1 << 20)
        self.eager_max = eager_max
        # late-bound tuner getter (the driver attaches its tuner to the
        # device AFTER device construction): prices the eager/rendezvous
        # crossover when no explicit threshold/env override exists
        self.tuner_fn = tuner_fn
        # put-with-notify completion queue (accl_tpu/rma/notify.py):
        # the target-side landing points push; the serving loop polls —
        # a rank-LOCAL dequeue, never a collective
        self.notify = NotifyQueue()
        self.rto_s = float(rto_s)
        self.max_tries = int(max_tries)
        self.tier = tier
        self._mu = threading.Lock()
        self._tx: dict[int, _Tx] = {}
        self._rx: dict[tuple[int, int], _Rx] = {}
        self._srv: dict[tuple[int, int], _Srv] = {}
        # completed inbound transfers: duplicate RTS/DONE/EAGER after
        # completion re-FIN from here instead of re-running (bounded)
        self._done_memo: dict[tuple[int, int], int] = {}
        # xfer ids carry the initiator's rank so two ranks' concurrent
        # transfers over the same pair can never collide at either end
        self._next = itertools.count(1)
        self._jobs: queue.Queue | None = None
        self._worker: threading.Thread | None = None
        self._closed = False
        # engine-local counters, folded into the registry by a weak
        # collector (per-segment registry incs would pay the process-wide
        # lock on every frame — the storm-shaped cost the daemon/driver
        # collectors exist to avoid)
        self.counters: dict[str, int] = {}
        METRICS.register_collector(self, RmaEngine.metrics_rows)

    # -- lifecycle ---------------------------------------------------------
    def _ensure_worker(self):
        if self._jobs is None:
            with self._mu:
                if self._jobs is None:
                    self._jobs = queue.Queue()
                    self._worker = threading.Thread(
                        target=self._run, daemon=True,
                        name=f"rma-tx{self.rank}")
                    self._worker.start()

    def close(self):
        self._closed = True
        if self._jobs is not None:
            self._jobs.put(None)
        with self._mu:
            pending = list(self._tx.values())
            self._tx.clear()
            self._rx.clear()
            self._srv.clear()
        for st in pending:
            st.handle.complete(int(ErrorCode.CONNECTION_CLOSED))

    def reset(self):
        """Rank-local soft reset: in-flight transfer state dies with the
        seqn spaces (initiator handles fail typed — a reset mid-transfer
        is the existing soft-reset contract, rank-local surgery)."""
        with self._mu:
            pending = list(self._tx.values())
            self._tx.clear()
            self._rx.clear()
            self._srv.clear()
            self._done_memo.clear()
        self.notify.clear()
        for st in pending:
            st.handle.complete(int(ErrorCode.CONNECTION_CLOSED))

    def _count(self, key: str, n: int = 1):
        self.counters[key] = self.counters.get(key, 0) + n

    def metrics_rows(self):
        labels = {"rank": self.rank, "tier": self.tier}
        for k, v in list(self.counters.items()):
            yield ("counter", k, labels, v)
        yield ("gauge", "rma_inflight", labels, len(self._tx))
        nq = self.notify
        if nq.enqueued:
            yield ("counter", "notify_enqueued_total", labels, nq.enqueued)
        if nq.polled:
            yield ("counter", "notify_polled_total", labels, nq.polled)
        if nq.dropped:
            yield ("counter", "notify_dropped_total", labels, nq.dropped)
        pend = nq.pending()
        if pend:
            yield ("gauge", "notify_pending", labels, pend)

    # -- eager/rendezvous crossover ----------------------------------------
    def effective_eager_max(self) -> int:
        """The live eager threshold. Precedence: explicit constructor
        value > ``$ACCL_TPU_RMA_EAGER_MAX`` (the operator override always
        wins when set) > the attached tuner's alpha-beta-priced,
        measurement-refined recommendation > the static default."""
        if self.eager_max is not None:
            return self.eager_max
        if os.environ.get("ACCL_TPU_RMA_EAGER_MAX") is not None:
            return eager_max_from_env()
        tuner = self.tuner_fn() if self.tuner_fn is not None else None
        if tuner is not None:
            rec = getattr(tuner, "recommend_rma_eager_max", None)
            if rec is not None:
                try:
                    got = rec()
                    if got:
                        return int(got)
                except Exception:  # noqa: BLE001 — a broken tuner must
                    pass           # not take the put path down with it
        return DEFAULT_RMA_EAGER_MAX

    # -- initiator ---------------------------------------------------------
    def start(self, scenario: CCLOp, comm, target: int, window: int,
              offset: int, count: int, arithcfg, eth_compressed: bool,
              local_addr: int, handle: CallHandle, tenant: str = "",
              local_compressed: bool = False, notify: int | None = None):
        """Begin one put/get. ``target`` is the comm-local rank index (the
        descriptor's root_src_dst), ``local_addr`` the initiator's source
        (put) / destination (get) byte address — stored in the COMPRESSED
        dtype when ``local_compressed`` (the descriptor's OP0/RES
        compression flag; the window side always holds the uncompressed
        dtype). ``notify`` (puts only) is a request token the TARGET
        enqueues on its completion queue when the data lands. Returns
        immediately; the handle completes when the target FINs (put) or
        every segment landed (get)."""
        if self._closed:
            handle.complete(int(ErrorCode.CONNECTION_CLOSED))
            return
        if not (0 <= target < comm.size):
            handle.complete(int(ErrorCode.INVALID_CALL))
            return
        u_dt = arithcfg.uncompressed_dtype
        l_dt = (arithcfg.compressed_dtype if local_compressed else u_dt)
        if target == comm.local_rank:
            # local shortcut: a self-put/get is a window-checked memcpy
            self._local_copy(scenario, window, offset, count, arithcfg,
                             local_addr, l_dt, handle, notify=notify)
            return
        w_dt = (arithcfg.compressed_dtype if eth_compressed
                else arithcfg.uncompressed_dtype)
        plan = plan_transfer(count, u_dt.itemsize, w_dt.itemsize,
                             self.seg_fn(), self.effective_eager_max())
        xfer = ((self.rank & 0x7FF) << 20) | (next(self._next) & 0xFFFFF)
        st = _Tx(kind=scenario, xfer=xfer, comm=comm,
                 comm_id=comm.comm_id,
                 dst=comm.ranks[target].global_rank, window=int(window),
                 offset=int(offset), count=int(count), u_dtype=u_dt,
                 w_dtype=w_dt, l_dtype=l_dt, eth_c=bool(eth_compressed),
                 addr=int(local_addr), plan=plan, handle=handle,
                 tenant=tenant or self.tenant_of(comm.comm_id),
                 phase="", tries=0,
                 # a real (not 0) deadline from the outset: the retry
                 # tick must not race the queued initial emission into a
                 # spurious duplicate
                 deadline=time.monotonic() + self._rto(0), got=set(),
                 done_seen=False, t0=time.perf_counter(),
                 notify=(notify if scenario == CCLOp.put else None))
        with self._mu:
            self._tx[xfer] = st
        self._ensure_worker()
        if scenario == CCLOp.get:
            self._count("rma_gets_total")
            st.phase = "get"
            self._enqueue(("get", xfer))
        elif plan.kind == EAGER:
            self._count("rma_puts_total")
            self._count("rma_eager_total")
            st.phase = "eager"
            self._enqueue(("eager", xfer))
        else:
            self._count("rma_puts_total")
            self._count("rma_rendezvous_total")
            st.phase = "rts"
            self._enqueue(("rts", xfer))

    def _local_copy(self, scenario, window, offset, count, arithcfg,
                    local_addr, l_dt, handle, notify=None):
        try:
            dt = arithcfg.uncompressed_dtype
            base = self.windows.resolve(window, offset, count * dt.itemsize)
            if scenario == CCLOp.put:
                data = self.mem.read(local_addr, count, l_dt)
                self.mem.write(base, np.ascontiguousarray(
                    data.astype(dt, copy=False)))
                if notify is not None:
                    self._notify_push(notify, window, self.rank, 0,
                                      offset, count * dt.itemsize)
            else:
                data = self.mem.read(base, count, dt)
                self.mem.write(local_addr, np.ascontiguousarray(
                    data.astype(l_dt, copy=False)))
            handle.complete(0)
        except ACCLError as exc:
            self._count("rma_window_errors_total")
            if scenario == CCLOp.put and notify is not None:
                self._notify_push(notify, window, self.rank,
                                  exc.error_word, offset, 0)
            handle.complete(exc.error_word, exception=exc)
        except Exception as exc:  # noqa: BLE001 — surface, never hang
            handle.complete(int(ErrorCode.INVALID_CALL), exception=exc)

    def _notify_push(self, token: int, window: int, src: int, err: int,
                     offset: int, nbytes: int):
        self.notify.push(NotifyRecord(token=int(token), window=int(window),
                                      src=int(src), err=int(err),
                                      offset=int(offset),
                                      nbytes=int(nbytes)))

    def _enqueue(self, job):
        self._jobs.put(job)

    # -- TX worker (streaming + control emission + retry ticks) ------------
    def _run(self):
        tick = max(0.005, self.rto_s / 2)
        while True:
            try:
                job = self._jobs.get(timeout=tick)
            except queue.Empty:
                self._tick()
                continue
            if job is None:
                return
            try:
                self._run_job(job)
            except Exception:  # noqa: BLE001 — a failed job must not
                # kill the engine's only worker; the transfer's retry
                # tick (or give-up) owns the outcome
                log.error("rank %d rma: job %s failed", self.rank, job[0],
                          exc_info=True, extra={"rank": self.rank})
            self._tick()

    def _run_job(self, job):
        kind = job[0]
        if kind in ("rts", "eager", "get"):
            with self._mu:
                st = self._tx.get(job[1])
            if st is not None:
                self._send_initial(st)
        elif kind == "stream":
            with self._mu:
                st = self._tx.get(job[1])
            if st is not None:
                self._stream_put(st, job[2])
        elif kind == "serve":
            with self._mu:
                sv = self._srv.get(job[1])
            if sv is not None:
                self._stream_serve(job[1], sv, job[2])

    def _ctl(self, dst: int, comm_id: int, xfer: int, body: bytes):
        env = Envelope(src=self._my_global(comm_id), dst=dst, tag=xfer,
                       seqn=0, nbytes=len(body), wire_dtype="uint8",
                       strm=P.RMA_STRM, comm_id=comm_id)
        self._send(env, body)

    def _my_global(self, comm_id: int) -> int:
        comm = self.comm_of(comm_id)
        return comm.my_global_rank if comm is not None else self.rank

    def _send_initial(self, st: _Tx):
        """Emit (or re-emit) the transfer's opening frame."""
        kind = {"rts": P.RMA_RTS, "get": P.RMA_GET,
                "eager": P.RMA_EAGER}[st.phase] if st.phase in (
                    "rts", "get", "eager") else None
        if kind is None:
            return  # phase advanced while the job sat queued
        payload = b""
        if kind == P.RMA_EAGER:
            data = self.mem.read(st.addr, st.count, st.l_dtype, copy=False)
            payload = np.ascontiguousarray(
                data.astype(st.w_dtype, copy=False)).tobytes()
        body = P.pack_rma_ctl(
            kind, st.xfer, window=st.window, offset=st.offset,
            count=st.count, udtype=P.dtype_code(st.u_dtype),
            cdtype=P.dtype_code(st.w_dtype), eth_compressed=st.eth_c,
            nsegs=st.plan.nsegs, notify=st.notify, payload=payload)
        st.deadline = time.monotonic() + self._rto(st.tries)
        if TRACE.enabled:
            TRACE.emit("rma_" + st.phase, rank=self.rank, seqn=st.xfer,
                       peer=st.dst, nbytes=st.plan.wire_bytes,
                       tenant=st.tenant)
        try:
            self._ctl(st.dst, st.comm_id, st.xfer, body)
        except (RuntimeError, KeyError, OSError, ConnectionError):
            pass  # unreachable peer: the retry tick (and give-up) own it

    def _rto(self, tries: int) -> float:
        return min(self.rto_s * (1 << min(tries, 6)), 2.0)

    def _stream_put(self, st: _Tx, indices):
        """Stream (all, or the NACKed subset of) a put's segments into
        the wire, then DONE. Runs on the TX worker so async puts overlap
        the issuing thread's compute."""
        segs = (range(st.plan.nsegs) if indices is None else indices)
        my = self._my_global(st.comm_id)
        resend = indices is not None
        try:
            for si in segs:
                off, n = st.plan.segments[si]
                # local source in ITS stored dtype (OP0_COMPRESSED puts
                # store the compressed form); the window side is always
                # the uncompressed dtype
                data = self.mem.read(st.addr + off * st.l_dtype.itemsize,
                                     n, st.l_dtype, copy=False)
                wire = np.ascontiguousarray(
                    data.astype(st.w_dtype, copy=False))
                payload = wire.reshape(-1).view(np.uint8)
                env = Envelope(src=my, dst=st.dst, tag=st.xfer, seqn=si,
                               nbytes=payload.nbytes,
                               wire_dtype=st.w_dtype.name,
                               strm=P.RMA_DATA_STRM, comm_id=st.comm_id)
                self._send(env, payload)
                self._count("rma_segments_total")
                if resend:
                    self._count("rma_retransmits_total")
                # progress refreshes the stall deadline: _tick only
                # intervenes in a stream that stopped emitting
                st.deadline = time.monotonic() + max(
                    1.0, self._rto(st.tries))
                if TRACE.enabled:
                    TRACE.emit("rma_seg", rank=self.rank, seqn=si,
                               peer=st.dst, nbytes=payload.nbytes,
                               tenant=st.tenant)
            st.phase = "done"
            st.deadline = time.monotonic() + self._rto(st.tries)
            self._ctl(st.dst, st.comm_id, st.xfer, P.pack_rma_ctl(
                P.RMA_DONE, st.xfer, count=st.count,
                nsegs=st.plan.nsegs))
        except (RuntimeError, KeyError, OSError, ConnectionError):
            # mid-stream failure (fabric tearing down, peer gone, bad
            # local range): hand recovery to the DONE/NACK machinery —
            # the receiver NACKs whatever is missing, and the retry
            # tick's give-up bound turns a dead peer into a typed
            # timeout instead of a hung handle
            st.phase = "done"
            st.deadline = time.monotonic()

    def _stream_serve(self, key, sv: _Srv, indices):
        """Target side of a get: stream the requested window region back
        to the requester, then DONE."""
        src, xfer = key
        segs = (range(sv.nsegs) if indices is None else indices)
        my = self._my_global(sv.comm_id)
        try:
            for si in segs:
                off, n = sv.bounds[si]
                data = self.mem.read(sv.base + off * sv.u_dtype.itemsize,
                                     n, sv.u_dtype, copy=False)
                wire = np.ascontiguousarray(
                    data.astype(sv.w_dtype, copy=False))
                payload = wire.reshape(-1).view(np.uint8)
                env = Envelope(src=my, dst=src, tag=xfer, seqn=si,
                               nbytes=payload.nbytes,
                               wire_dtype=sv.w_dtype.name,
                               strm=P.RMA_DATA_STRM, comm_id=sv.comm_id)
                self._send(env, payload)
                self._count("rma_segments_total")
                if indices is not None:
                    self._count("rma_retransmits_total")
            self._ctl(src, sv.comm_id, xfer, P.pack_rma_ctl(
                P.RMA_DONE, xfer, count=sv.count, nsegs=sv.nsegs))
            # a served (or re-served) transfer stays NACKable for a
            # fresh TTL — the GC guards abandoned serves, not live ones
            sv.expires = time.monotonic() + self.timeout_fn()
        except (RuntimeError, KeyError, OSError, ConnectionError):
            pass  # requester's own retry (re-GET / NACK) recovers

    # -- retry ticks -------------------------------------------------------
    def _tick(self):
        now = time.monotonic()
        expired: list[_Tx] = []
        gave_up: list[_Tx] = []
        with self._mu:
            for st in self._tx.values():
                if st.deadline > now:
                    continue
                if st.phase == "stream":
                    # the streaming job stalled (its per-segment deadline
                    # refresh stopped): fall to the DONE path — the
                    # receiver NACKs whatever is missing, and the tries
                    # bound below still owns give-up
                    st.phase = "done"
                st.tries += 1
                if st.tries > self.max_tries:
                    gave_up.append(st)
                else:
                    expired.append(st)
            for st in gave_up:
                self._tx.pop(st.xfer, None)
            for key in [k for k, rx in self._rx.items()
                        if rx.expires < now]:
                del self._rx[key]
            for key in [k for k, sv in self._srv.items()
                        if sv.expires < now]:
                del self._srv[key]
        for st in gave_up:
            self._count("rma_gave_up_total")
            log.warning(
                "rank %d rma: %s xfer %#x to rank %d gave up after %d "
                "tries (phase %s)", self.rank, st.kind.name, st.xfer,
                st.dst, self.max_tries, st.phase,
                extra={"rank": self.rank})
            st.handle.complete(int(ErrorCode.RECEIVE_TIMEOUT_ERROR))
        for st in expired:
            self._count("rma_retransmits_total")
            st.deadline = now + self._rto(st.tries)
            if st.phase in ("rts", "eager"):
                self._send_initial(st)
            elif st.phase == "done":
                try:
                    self._ctl(st.dst, st.comm_id, st.xfer, P.pack_rma_ctl(
                        P.RMA_DONE, st.xfer, count=st.count,
                        nsegs=st.plan.nsegs))
                except (RuntimeError, KeyError, OSError, ConnectionError):
                    pass
            elif st.phase == "get":
                if not st.got:
                    self._send_initial(st)
                else:
                    missing = [i for i in range(st.plan.nsegs)
                               if i not in st.got]
                    try:
                        self._ctl(st.dst, st.comm_id, st.xfer,
                                  P.pack_rma_ctl(P.RMA_NACK, st.xfer,
                                                 extra=missing))
                    except (RuntimeError, KeyError, OSError,
                            ConnectionError):
                        pass

    # -- ingress (both RMA strm lanes route here) --------------------------
    def on_frame(self, env: Envelope, payload):
        if env.csum is not None and self.csum_fn() \
                and P.csum_of(payload) != env.csum:
            # One-sided lanes bypass the rx pool (rendezvous segments
            # land DIRECTLY in windows), so they get their own landing
            # verify, against the engine's own recovery machinery: a
            # corrupt segment is simply never recorded in the per-index
            # ``got`` set — the post-DONE NACK path re-fetches exactly
            # it — and a corrupt control frame is dropped like a lost
            # one (initiator RTS/GET/DONE retries re-elicit it).
            self._count("rma_integrity_failed_total")
            METRICS.inc("integrity_failed_total", fabric="rma",
                        comm_id=env.comm_id, src=env.src, dst=env.dst)
            if TRACE.enabled:
                TRACE.emit("integrity_drop", rank=self.rank,
                           seqn=env.seqn, peer=env.src,
                           nbytes=env.nbytes)
            return
        if env.strm == P.RMA_DATA_STRM:
            self._on_data(env, payload)
            return
        ctl, trailing = P.unpack_rma_ctl(payload)
        kind = ctl["kind"]
        if kind == P.RMA_RTS:
            self._on_rts(env, ctl)
        elif kind == P.RMA_CTS:
            self._on_cts(env, ctl)
        elif kind == P.RMA_GET:
            self._on_get(env, ctl)
        elif kind == P.RMA_DONE:
            self._on_done(env, ctl)
        elif kind == P.RMA_FIN:
            self._on_fin(env, ctl)
        elif kind == P.RMA_NACK:
            self._on_nack(env, P.unpack_rma_nack(trailing))
        elif kind == P.RMA_EAGER:
            self._on_eager(env, ctl, trailing)
        else:
            self._count("rma_orphan_frames_total")

    def _resolve_target(self, ctl) -> tuple[int, np.dtype, np.dtype]:
        u_dt = P.code_dtype(ctl["udtype"])
        w_dt = P.code_dtype(ctl["cdtype"]) if ctl["eth_compressed"] \
            else u_dt
        base = self.windows.resolve(ctl["window"], ctl["offset"],
                                    ctl["count"] * u_dt.itemsize)
        return base, u_dt, w_dt

    def _fin(self, dst: int, comm_id: int, xfer: int, err: int = 0):
        try:
            self._ctl(dst, comm_id, xfer, P.pack_rma_ctl(
                P.RMA_FIN, xfer, err=err))
        except (RuntimeError, KeyError, OSError, ConnectionError):
            pass  # initiator's DONE/RTS retry re-elicits the FIN

    def _memo_done(self, key, err: int):
        self._done_memo[key] = err
        while len(self._done_memo) > _DONE_MEMO_CAP:
            self._done_memo.pop(next(iter(self._done_memo)))

    # target side of a put rendezvous
    def _on_rts(self, env: Envelope, ctl):
        key = (env.src, ctl["xfer"])
        with self._mu:
            memo = self._done_memo.get(key)
            rx = self._rx.get(key)
        if memo is not None:
            self._fin(env.src, env.comm_id, ctl["xfer"], memo)
            return
        if rx is None:
            try:
                base, u_dt, w_dt = self._resolve_target(ctl)
            except ACCLError as exc:
                # memoize the typed failure so a retried RTS re-FINs
                # idempotently — and so the error notify (below) is
                # delivered exactly once, like a success notify
                self._count("rma_window_errors_total")
                with self._mu:
                    already = key in self._done_memo
                    self._memo_done(key, exc.error_word)
                if not already and ctl["notify"] is not None:
                    self._notify_push(ctl["notify"], ctl["window"],
                                      env.src, exc.error_word,
                                      ctl["offset"], 0)
                self._fin(env.src, env.comm_id, ctl["xfer"],
                          exc.error_word)
                return
            rx = _Rx(base=base, count=ctl["count"], u_dtype=u_dt,
                     w_dtype=w_dt, eth_c=ctl["eth_compressed"],
                     nsegs=ctl["nsegs"],
                     bounds=segment_bounds(ctl["count"], ctl["nsegs"]),
                     got=set(), comm_id=env.comm_id,
                     tenant=self.tenant_of(env.comm_id),
                     expires=time.monotonic() + self.timeout_fn(),
                     notify=ctl["notify"], window=ctl["window"],
                     offset=ctl["offset"])
            with self._mu:
                self._rx.setdefault(key, rx)
        # (duplicate RTS for a live transfer re-CTSes — the CTS may have
        # been the dropped frame)
        self._ctl(env.src, env.comm_id, ctl["xfer"],
                  P.pack_rma_ctl(P.RMA_CTS, ctl["xfer"]))
        if TRACE.enabled:
            TRACE.emit("rma_cts", rank=self.rank, seqn=ctl["xfer"],
                       peer=env.src, nbytes=0, tenant=rx.tenant)

    # initiator side: CTS arrived, stream the payload
    def _on_cts(self, env: Envelope, ctl):
        with self._mu:
            st = self._tx.get(ctl["xfer"])
            if st is None or st.phase != "rts":
                return  # duplicate CTS / already streaming
            st.phase = "stream"
        self._enqueue(("stream", st.xfer, None))

    # target side of a get
    def _on_get(self, env: Envelope, ctl):
        key = (env.src, ctl["xfer"])
        with self._mu:
            sv = self._srv.get(key)
        if sv is None:
            try:
                base, u_dt, w_dt = self._resolve_target(ctl)
            except ACCLError as exc:
                self._count("rma_window_errors_total")
                self._fin(env.src, env.comm_id, ctl["xfer"],
                          exc.error_word)
                return
            sv = _Srv(base=base, count=ctl["count"], u_dtype=u_dt,
                      w_dtype=w_dt, eth_c=ctl["eth_compressed"],
                      nsegs=ctl["nsegs"],
                      bounds=segment_bounds(ctl["count"], ctl["nsegs"]),
                      comm_id=env.comm_id, dst=env.src,
                      tenant=self.tenant_of(env.comm_id),
                      expires=time.monotonic() + self.timeout_fn())
            with self._mu:
                self._srv.setdefault(key, sv)
        self._ensure_worker()
        self._enqueue(("serve", key, None))

    # payload segment: target of a put, or initiator of a get
    def _on_data(self, env: Envelope, payload):
        key = (env.src, env.tag)
        with self._mu:
            rx = self._rx.get(key)
            st = self._tx.get(env.tag) if rx is None else None
        if rx is not None:
            si = env.seqn
            if si >= rx.nsegs or si in rx.got:
                return  # corrupt index / duplicate: idempotent-drop
            off, n = rx.bounds[si]
            self._land(rx.base, off, n, rx.u_dtype, rx.w_dtype, payload)
            with self._mu:
                rx.got.add(si)
                # a live stream keeps its state alive: the TTL guards
                # ABANDONED transfers, not slow (throttled-link) ones
                rx.expires = time.monotonic() + self.timeout_fn()
            return
        if st is not None and st.kind == CCLOp.get \
                and env.src == st.dst:
            si = env.seqn
            if si >= st.plan.nsegs or si in st.got:
                return
            off, n = st.plan.segments[si]
            self._land(st.addr, off, n, st.l_dtype, st.w_dtype, payload)
            with self._mu:
                st.got.add(si)
                # progress resets the give-up clock: the timeout bound
                # guards ABANDONED transfers, not slow/large ones (a
                # throttled-link get must not die of its own duration)
                st.tries = 0
                st.deadline = time.monotonic() + self._rto(0)
            self._maybe_finish_get(st)
            return
        self._count("rma_orphan_frames_total")

    def _land(self, base: int, elem_off: int, n: int, u_dt, w_dt, payload):
        """Decode a wire segment and write it at its landing offset —
        directly into registered memory, no intermediate buffering."""
        arr = np.frombuffer(payload, dtype=w_dt, count=n)
        self.mem.write(base + elem_off * u_dt.itemsize,
                       np.ascontiguousarray(arr.astype(u_dt, copy=False)))

    def _on_done(self, env: Envelope, ctl):
        key = (env.src, ctl["xfer"])
        with self._mu:
            rx = self._rx.get(key)
            st = self._tx.get(ctl["xfer"]) if rx is None else None
            memo = self._done_memo.get(key) if rx is None else None
        if rx is not None:
            missing = [i for i in range(rx.nsegs) if i not in rx.got]
            if missing:
                self._ctl(env.src, env.comm_id, ctl["xfer"],
                          P.pack_rma_ctl(P.RMA_NACK, ctl["xfer"],
                                         extra=missing))
                return
            with self._mu:
                popped = self._rx.pop(key, None)
                self._memo_done(key, 0)
            nbytes = rx.count * rx.u_dtype.itemsize
            if popped is not None and rx.notify is not None:
                # exactly-once boundary: only the DONE that transitions
                # the transfer into the memo enqueues — a duplicate DONE
                # racing here finds _rx already popped and only re-FINs
                self._notify_push(rx.notify, rx.window, env.src, 0,
                                  rx.offset, nbytes)
            self._count("rma_bytes_total", nbytes)
            self._fin(env.src, env.comm_id, ctl["xfer"], 0)
            if TRACE.enabled:
                TRACE.emit("rma_fin", rank=self.rank, seqn=ctl["xfer"],
                           peer=env.src,
                           nbytes=rx.count * rx.u_dtype.itemsize,
                           tenant=rx.tenant)
            return
        if st is not None and st.kind == CCLOp.get:
            st.done_seen = True
            self._maybe_finish_get(st, nack_now=True)
            return
        if memo is not None:
            # FIN was lost and the initiator re-DONEd: re-answer
            self._fin(env.src, env.comm_id, ctl["xfer"], memo)

    def _maybe_finish_get(self, st: _Tx, nack_now: bool = False):
        missing = None
        with self._mu:
            if st.xfer not in self._tx:
                return
            if len(st.got) >= st.plan.nsegs:
                self._tx.pop(st.xfer, None)
            elif st.done_seen and nack_now:
                missing = [i for i in range(st.plan.nsegs)
                           if i not in st.got]
            else:
                return
        if missing is not None:
            try:
                self._ctl(st.dst, st.comm_id, st.xfer, P.pack_rma_ctl(
                    P.RMA_NACK, st.xfer, extra=missing))
            except (RuntimeError, KeyError, OSError, ConnectionError):
                pass
            return
        self._count("rma_bytes_total", st.count * st.u_dtype.itemsize)
        self._fin(st.dst, st.comm_id, st.xfer, 0)  # releases _srv state
        self._complete(st, 0)

    def _on_fin(self, env: Envelope, ctl):
        # a FIN addressed to a get-serve releases the serve state; one
        # addressed to a put initiator completes the put
        key = (env.src, ctl["xfer"])
        with self._mu:
            if key in self._srv:
                del self._srv[key]
                return
            st = self._tx.pop(ctl["xfer"], None)
        if st is None:
            return
        self._complete(st, ctl["err"])

    def _complete(self, st: _Tx, err: int):
        if err:
            self._count("rma_window_errors_total" if err
                        & int(ErrorCode.RMA_WINDOW_ERROR)
                        else "rma_failed_total")
        elif st.kind == CCLOp.put and self.tuner_fn is not None:
            # feed the measured put latency back into the tuner's
            # eager/rendezvous crossover (clean completions only — a
            # retry-storm duration says nothing about the path's cost)
            tuner = self.tuner_fn()
            obs = getattr(tuner, "observe_rma_put", None)
            if obs is not None and st.tries == 0:
                try:
                    obs(st.count * st.u_dtype.itemsize,
                        st.plan.kind == EAGER,
                        time.perf_counter() - st.t0)
                except Exception:  # noqa: BLE001 — observability must
                    pass           # never fail the data path
        if TRACE.enabled:
            t0_ns = time.monotonic_ns() - int(
                (time.perf_counter() - st.t0) * 1e9)
            TRACE.emit(st.kind.name, rank=self.rank, seqn=st.xfer,
                       peer=st.dst, nbytes=st.count * st.u_dtype.itemsize,
                       t_ns=t0_ns,
                       dur_ns=int((time.perf_counter() - st.t0) * 1e9),
                       tenant=st.tenant)
        st.handle.complete(err)

    def _on_nack(self, env: Envelope, missing):
        with self._mu:
            st = self._tx.get(env.tag)
            sv = self._srv.get((env.src, env.tag)) if st is None else None
        if st is not None and st.kind == CCLOp.put:
            self._enqueue(("stream", st.xfer,
                           [i for i in missing
                            if i < st.plan.nsegs]))
        elif sv is not None:
            self._ensure_worker()
            self._enqueue(("serve", (env.src, env.tag),
                           [i for i in missing if i < sv.nsegs]))

    # target side of an eager put: ride the rx pool, then land
    def _on_eager(self, env: Envelope, ctl, payload):
        key = (env.src, ctl["xfer"])
        with self._mu:
            memo = self._done_memo.get(key)
        if memo is not None:
            # the FIN was lost and the initiator retried: re-answer from
            # the memo instead of re-running the pool ingest (which
            # would charge the tenant quota a second time — and rewrite
            # a window region the application may have moved on from)
            self._fin(env.src, env.comm_id, ctl["xfer"], memo)
            return
        try:
            base, u_dt, w_dt = self._resolve_target(ctl)
        except ACCLError as exc:
            self._count("rma_window_errors_total")
            with self._mu:
                already = key in self._done_memo
                self._memo_done(key, exc.error_word)
            if not already and ctl["notify"] is not None:
                # typed error delivery rides the same queue as success:
                # the serving poll loop learns of a failed put exactly
                # once (the memo absorbs retried EAGERs)
                self._notify_push(ctl["notify"], ctl["window"], env.src,
                                  exc.error_word, ctl["offset"], 0)
            self._fin(env.src, env.comm_id, ctl["xfer"], exc.error_word)
            return
        pool = self.pool_fn()
        if pool is not None:
            # The eager path's defining property: the payload claims a
            # spare rx buffer first — charging the comm's tenant quota,
            # obeying the oversize latch, backpressuring when the pool
            # is full — exactly like an eager-ingress collective
            # message, then is consumed straight back out and landed.
            # (Rendezvous transfers, by contrast, never touch the pool.)
            syn = Envelope(src=env.src, dst=env.dst, tag=ctl["xfer"],
                           seqn=_POOL_SEQ_BASE | (ctl["xfer"] & 0xFFFFFF),
                           nbytes=P.payload_nbytes(payload),
                           wire_dtype=w_dt.name, strm=0,
                           comm_id=env.comm_id)
            err = pool.ingest(syn, payload, timeout=self.timeout_fn())
            if err:
                self._count("rma_eager_rejected_total")
                if err & int(ErrorCode.DMA_SIZE_ERROR):
                    # oversize for THIS target's buffers: retrying the
                    # same frame cannot help — fail the put typed
                    self._fin(env.src, env.comm_id, ctl["xfer"], err)
                return  # overflow/quota: unFINed — the sender retries
            got = pool.seek(syn.src, syn.tag, syn.seqn,
                            timeout=self.timeout_fn(),
                            comm_id=syn.comm_id)
            if got is None:  # claimed by a duplicate's seek: that
                return       # duplicate lands and FINs for both
            payload = got[1]
        self._land(base, 0, ctl["count"], u_dt, w_dt, payload)
        nbytes = ctl["count"] * u_dt.itemsize
        self._count("rma_bytes_total", nbytes)
        with self._mu:
            already = key in self._done_memo
            self._memo_done(key, 0)
        if not already and ctl["notify"] is not None:
            # same exactly-once transition as the rendezvous DONE: the
            # memo write IS the completion event; duplicates that raced
            # past the top-of-handler memo check stop here
            self._notify_push(ctl["notify"], ctl["window"], env.src, 0,
                              ctl["offset"], nbytes)
        self._fin(env.src, env.comm_id, ctl["xfer"], 0)
