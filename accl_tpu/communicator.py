"""Communicators: the rank group + per-peer connection/sequence state.

Parity: the reference's communicator is a record in FPGA exchange memory —
{size, local_rank, then per-rank {ip, port, inbound_seq, outbound_seq,
session, max_segment_size}} (ccl_offload_control.h:271-298), written by
``configure_communicator`` (driver/pynq/accl.py:677-708) and dumped by
``dump_communicator`` (accl.py:710-735). Sequence numbers give per-sender
ordering; sessions identify transport connections.

TPU-native design: a communicator additionally binds to a ``jax.sharding``
mesh axis, so collectives over the communicator lower to XLA collectives
over that axis. For the emulator tier the per-rank (host, port) fields play
the reference's (ip, port) role on a framed-TCP fabric.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any, Sequence

from .constants import DEFAULT_MAX_SEGMENT_SIZE


@dataclasses.dataclass
class Rank:
    """Per-peer state within a communicator.

    Parity: per-rank exchange-memory record (ccl_offload_control.h:280-298).
    """

    host: str = "127.0.0.1"
    port: int = 0
    inbound_seq: int = 0
    outbound_seq: int = 0
    session: int = 0xFFFFFFFF
    max_segment_size: int = DEFAULT_MAX_SEGMENT_SIZE
    device: Any = None     # jax.Device when bound to a mesh
    global_rank: int = -1  # fabric endpoint id (world rank); comm-local rank
    #                        is this Rank's index in Communicator.ranks


@dataclasses.dataclass
class Communicator:
    """A group of ranks with a distinguished local rank.

    ``ranks`` order defines rank numbering. ``comm_id`` plays the role of the
    reference's communicator exchange-memory address (the host passes it in
    the call descriptor, accl.py:596). It is derived deterministically from
    the membership (+ ``key`` to disambiguate same-membership comms), so
    every member computes the same id without a handshake.
    """

    ranks: list[Rank]
    local_rank: int
    comm_id: int | None = None
    mesh_axis: str | None = None  # mesh axis name when TPU-backed
    key: int = 0                  # disambiguates same-membership comms
    # ULFM-style revocation (failure containment): once revoked — the
    # application's reaction to observing ErrorCode.PEER_FAILED — the
    # driver refuses further calls on this communicator; survivors
    # rebuild via ACCL.shrink_communicator. Rank-local, like the
    # failure observation itself. Splits never inherit it (a shrunken
    # survivor comm starts healthy).
    revoked: bool = False

    def revoke(self):
        self.revoked = True

    def __post_init__(self):
        # default global ranks to comm-local numbering (the world comm case)
        for i, r in enumerate(self.ranks):
            if r.global_rank < 0:
                r.global_rank = i
        if self.comm_id is None:
            members = ",".join(str(r.global_rank) for r in self.ranks)
            self.comm_id = zlib.crc32(f"{members}#{self.key}".encode())

    @property
    def size(self) -> int:
        return len(self.ranks)

    def global_rank_of(self, local: int) -> int:
        return self.ranks[local].global_rank

    @property
    def my_global_rank(self) -> int:
        return self.ranks[self.local_rank].global_rank

    def next_rank(self) -> int:
        return (self.local_rank + 1) % self.size

    def prev_rank(self) -> int:
        return (self.local_rank - 1) % self.size

    def membership_signature(self) -> int:
        """Deterministic digest of (membership, ADDRESS TABLE, key) —
        the value every member's join hello carries (elastic
        membership, ACCL.grow_communicator). Deliberately covers MORE
        than the comm_id derivation (which is membership+key alone):
        two members growing the same comm id but disagreeing on a
        member's (host, port) — e.g. a re-addressed rejoiner one
        survivor learned about and another did not — mismatch here and
        fail the handshake typed (JOIN_FAILED), instead of completing
        a bootstrap whose first collective dials a stale address."""
        table = ",".join(f"{r.global_rank}:{r.host}:{r.port}"
                         for r in self.ranks)
        return zlib.crc32(f"{table}#{self.key}".encode())

    def split(self, members: Sequence[int], new_local: int | None = None,
              key: int = 0) -> "Communicator":
        """Create a sub-communicator from a subset of ranks.

        Parity: the reference's driver can write multiple communicators into
        exchange memory (split capability exercised by multi-CCLO tests).
        """
        # fresh sequence counters: seqn matching is scoped per comm_id, so a
        # sub-comm must start at 0 on every member regardless of world-comm
        # traffic in flight at split time
        sub = [dataclasses.replace(self.ranks[m], inbound_seq=0,
                                   outbound_seq=0) for m in members]
        if new_local is None:
            if self.local_rank not in members:
                raise ValueError("local rank not in sub-communicator")
            new_local = list(members).index(self.local_rank)
        return Communicator(ranks=sub, local_rank=new_local,
                            mesh_axis=self.mesh_axis, key=key)

    def describe(self) -> str:
        """Human-readable dump. Parity: dump_communicator (accl.py:710-735)."""
        lines = [f"Communicator {self.comm_id}: size={self.size} "
                 f"local_rank={self.local_rank} mesh_axis={self.mesh_axis}"]
        for i, r in enumerate(self.ranks):
            lines.append(
                f"  rank {i}: addr={r.host}:{r.port} session={r.session} "
                f"in_seq={r.inbound_seq} out_seq={r.outbound_seq} "
                f"max_seg={r.max_segment_size}"
                + (f" device={r.device}" if r.device is not None else ""))
        return "\n".join(lines)


def grown_communicator(rank_records: Sequence[Rank], my_global_rank: int,
                       mesh_axis: str | None = None,
                       key: int = 0) -> Communicator:
    """Build a grown communicator from per-member Rank records (elastic
    membership, ACCL.grow_communicator): members are ordered by GLOBAL
    rank so every participant — survivor or joiner — derives the
    identical rank numbering (and therefore comm_id) without a
    handshake, the split_communicator determinism contract. Fresh
    sequence counters on every member: a grown membership is a new (or
    restarted) seqn space, never an inheritance of the old one."""
    by_g: dict[int, Rank] = {}
    for r in rank_records:
        if r.global_rank < 0:
            raise ValueError("grown members need explicit global ranks")
        by_g.setdefault(r.global_rank, r)
    ranks = [dataclasses.replace(by_g[g], inbound_seq=0, outbound_seq=0)
             for g in sorted(by_g)]
    local = next((i for i, r in enumerate(ranks)
                  if r.global_rank == my_global_rank), None)
    if local is None:
        raise ValueError(f"local global rank {my_global_rank} is not a "
                         f"member of the grown communicator "
                         f"{sorted(by_g)}")
    return Communicator(ranks=ranks, local_rank=local,
                        mesh_axis=mesh_axis, key=key)


def simple_communicator(world_size: int, local_rank: int,
                        base_port: int = 0) -> Communicator:
    """Build a localhost communicator for the emulator tier.

    Rank r listens on base_port + r (the reference's emulator binds cmd port
    base+rank and eth port base+W+rank, test/zmq/zmq_intf.cpp:36-63).
    """
    ranks = [Rank(host="127.0.0.1", port=(base_port + r if base_port else 0))
             for r in range(world_size)]
    return Communicator(ranks=ranks, local_rank=local_rank)
