"""Core operation codes, flags and error codes for the ACCL-TPU framework.

This module defines the public call surface of the framework: the operation
codes a host issues, the configuration sub-functions, reduction functions,
wire-compression flags, streaming flags, and the error codes every execution
engine can raise.

Capability parity: the reference exposes the same surface as Python enums in
``driver/pynq/accl.py:162-284`` (``CCLOp``, ``CCLOCfgFunc``,
``ACCLReduceFunctions``, ``ACCLCompressionFlags``, ``ACCLStreamFlags``,
``ErrorCode``). The numeric values here are our own; only the *semantics* are
preserved so a user of the reference finds every knob they had.
"""

from __future__ import annotations

import enum


class CCLOp(enum.IntEnum):
    """Primitive and collective operations accepted by a device backend.

    Parity: reference ``CCLOp`` (driver/pynq/accl.py:162-177).
    """

    config = 0
    copy = 1
    combine = 2
    send = 3
    recv = 4
    bcast = 5
    scatter = 6
    gather = 7
    reduce = 8
    allgather = 9
    allreduce = 10
    reduce_scatter = 11
    barrier = 12
    alltoall = 13
    # one-sided RMA (accl_tpu/rma): data lands in / is read from a
    # REGISTERED WINDOW on the target rank, which posts no matching call.
    # root_src_dst carries the target rank, tag the window id, addr_1 the
    # byte offset into the window — the descriptor shape rides the
    # existing 15-word wire format unchanged.
    put = 14
    get = 15
    # variable-count all-to-all (MPI_Alltoallv shape): per-peer send/recv
    # element counts ride OUTSIDE the fixed descriptor words as a count
    # vector (CallDescriptor.counts; an optional trailing record on the
    # socket wire). ``count`` still carries max(sum(send), sum(recv)) so
    # every byte-bound check (MAX_CALL_BYTES, plan relocation extent)
    # keeps working unchanged.
    alltoallv = 16
    nop = 255


class CfgFunc(enum.IntEnum):
    """Sub-functions of ``CCLOp.config``.

    Parity: reference ``CCLOCfgFunc`` (driver/pynq/accl.py:179-187) — reset,
    timeout, open port/connection, stack selection, segment size. TPU-native
    additions keep the same "runtime reconfiguration" capability over a mesh
    fabric instead of a TCP/UDP stack.
    """

    reset_periph = 0
    enable_pkt = 1
    set_timeout = 2
    open_port = 3
    open_con = 4
    set_stack_type = 5
    set_max_segment_size = 6
    close_con = 7
    start_profiling = 8
    end_profiling = 9


class ReduceFunc(enum.IntEnum):
    """Elementwise reduction functions.

    Parity: reference ``ACCLReduceFunctions`` (driver/pynq/accl.py:189-191)
    only ships SUM; the older XRT driver enumerates max as well
    (driver/xrt/include/xlnx-consts.hpp). We support the full MPI-style set —
    on TPU every one of these lowers to the same XLA reduction machinery.
    """

    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3


class Compression(enum.IntFlag):
    """Wire/operand precision-reduction flags.

    Parity: reference ``ACCLCompressionFlags`` (driver/pynq/accl.py:193-199).
    ``OP0/OP1/RES_COMPRESSED`` mark an operand already stored compressed;
    ``ETH_COMPRESSED`` requests compression on the wire only. On TPU "the
    wire" is ICI, and compression means running the collective in the
    compressed dtype (bf16/fp16/fp8) with decompress-on-arrival.
    """

    NONE = 0
    OP0_COMPRESSED = 1
    OP1_COMPRESSED = 2
    RES_COMPRESSED = 4
    ETH_COMPRESSED = 8
    # Block-scaled quantized wire (accl_tpu/quant.py, EQuARX-style):
    # only meaningful WITH ETH_COMPRESSED — each wire segment carries a
    # per-block absmax-derived f32 scale header ahead of the fp8/int8
    # payload, and the executor's combine lane runs the fused
    # dequant -> f32-accumulate -> requant step per hop. Operand storage
    # stays the uncompressed dtype (OP*/RES_COMPRESSED are rejected in
    # combination); the block size is a runtime, tuner-recommended
    # choice carried OUTSIDE this flag (descriptor qblock field /
    # ArithConfig.quant_block) because the payload is self-describing.
    BLOCK_SCALED = 16


class StreamFlags(enum.IntFlag):
    """Operand streaming flags.

    Parity: reference ``ACCLStreamFlags`` (driver/pynq/accl.py:201-205). In
    the reference, OP0/RES can be AXI streams wired to a user kernel; on TPU
    the analog is fusing the producer/consumer computation into the same XLA
    program as the collective (no materialized HBM buffer).
    """

    NO_STREAM = 0
    OP0_STREAM = 1
    RES_STREAM = 2


class CollectiveAlgorithm(enum.IntEnum):
    """Per-call collective algorithm selector.

    Parity: the reference's older XRT driver enumerates sw/hw, ring and
    round-robin variants per collective as distinct opcodes —
    ``bcast_rr``, ``gather_ring``, ``reduce_ring``, ``allreduce_fused_ring``
    ... (driver/xrt/include/xlnx-consts.hpp:43-66). We express the same
    design axis as an explicit selector on the call descriptor. AUTO picks
    each backend's default (the current firmware algorithms on the
    emulator tier; XLA's choice on the TPU tier).
    """

    AUTO = 0
    RING = 1          # ring / daisy-chain (reference *_ring)
    ROUND_ROBIN = 2   # direct root-centric sends (reference *_rr)
    TREE = 3          # binomial tree (2D-mesh trees live in parallel/tree.py)
    FUSED_RING = 4    # allreduce: fused ring reduce-scatter + allgather
    NON_FUSED = 5     # allreduce: reduce to root 0 then bcast
    # log-depth family (moveengine expansions; the latency regime ACCL+
    # arXiv:2312.11742 shows algorithm choice dominating): recursive
    # doubling allgather, recursive halving reduce_scatter, Rabenseifner
    # allreduce (halving reduce-scatter + doubling allgather). Non-power
    # of-2 worlds fold to 2^floor(log2 W) vranks in pre/post phases.
    RECURSIVE_DOUBLING = 6
    # two-tier hierarchical program (accl_tpu/hier): NOT a moveengine
    # expansion — the DRIVER lowers the call to a waitfor-chained phase
    # program of flat collectives over intra-host / inter-host
    # sub-communicators (reduce-scatter inner -> allreduce outer ->
    # allgather inner for allreduce; see hier/engine.py for the other
    # shapes). Descriptors therefore never carry this value to a
    # backend; the tuner selects it from a two-tier MeshTopology
    # (hier/topology.py) exactly when the inter-tier link is the
    # bottleneck ("Memory-efficient array redistribution", PAPERS.md).
    HIERARCHICAL = 7


# Which algorithms each collective accepts (AUTO is always legal). Every
# tier — move engine, python/native daemons, TPU backend — validates against
# this one table so a program behaves identically when moved across tiers.
VALID_ALGORITHMS: dict[str, frozenset] = {
    "bcast": frozenset({CollectiveAlgorithm.ROUND_ROBIN,
                        CollectiveAlgorithm.TREE,
                        CollectiveAlgorithm.HIERARCHICAL}),
    "scatter": frozenset({CollectiveAlgorithm.ROUND_ROBIN}),
    "gather": frozenset({CollectiveAlgorithm.RING,
                         CollectiveAlgorithm.ROUND_ROBIN,
                         CollectiveAlgorithm.TREE}),
    "reduce": frozenset({CollectiveAlgorithm.RING,
                         CollectiveAlgorithm.ROUND_ROBIN,
                         CollectiveAlgorithm.TREE}),
    "allgather": frozenset({CollectiveAlgorithm.RING,
                            CollectiveAlgorithm.ROUND_ROBIN,
                            CollectiveAlgorithm.RECURSIVE_DOUBLING,
                            CollectiveAlgorithm.HIERARCHICAL}),
    "allreduce": frozenset({CollectiveAlgorithm.RING,
                            CollectiveAlgorithm.FUSED_RING,
                            CollectiveAlgorithm.NON_FUSED,
                            CollectiveAlgorithm.RECURSIVE_DOUBLING,
                            CollectiveAlgorithm.HIERARCHICAL}),
    "reduce_scatter": frozenset({CollectiveAlgorithm.RING,
                                 CollectiveAlgorithm.RECURSIVE_DOUBLING,
                                 CollectiveAlgorithm.HIERARCHICAL}),
}

# Ops the driver can lower to a hierarchical two-tier phase program
# (accl_tpu/hier). HIERARCHICAL appears in VALID_ALGORITHMS only for
# these; it is never a static default and never reaches a backend in a
# descriptor (the driver intercepts it before issue).
HIERARCHICAL_OPS = frozenset({"bcast", "allgather", "allreduce",
                              "reduce_scatter"})


# What AUTO resolves to when no tuner is attached: one table shared by the
# move engine's dispatch and the tuner's fallback path, so the static
# defaults cannot drift between the two resolvers. The log-depth family
# (RECURSIVE_DOUBLING / rooted TREE) is deliberately NOT a static default:
# untuned AUTO keeps the size-independent ring/rr behavior every tier
# (including the native daemon) implements, and the size-aware switch to
# log-depth at small nbytes is the tuner's job (tuner/cost.py).
DEFAULT_ALGORITHMS: dict[str, CollectiveAlgorithm] = {
    "bcast": CollectiveAlgorithm.ROUND_ROBIN,
    "scatter": CollectiveAlgorithm.ROUND_ROBIN,
    "gather": CollectiveAlgorithm.RING,
    "reduce": CollectiveAlgorithm.RING,
    "allgather": CollectiveAlgorithm.RING,
    "allreduce": CollectiveAlgorithm.FUSED_RING,
    "reduce_scatter": CollectiveAlgorithm.RING,
}


def check_algorithm(scenario_name: str, algorithm) -> None:
    """Raise ValueError unless (scenario, algorithm) is a legal pair."""
    if algorithm == CollectiveAlgorithm.AUTO:
        return
    valid = VALID_ALGORITHMS.get(scenario_name)
    if valid is None:
        # ops like send/recv/copy have no algorithm axis at all — say so
        # instead of printing a baffling "valid: []"
        raise ValueError(
            f"{scenario_name} has no algorithm variants; only "
            f"CollectiveAlgorithm.AUTO is accepted, got "
            f"{CollectiveAlgorithm(algorithm).name}")
    if algorithm not in valid:
        raise ValueError(
            f"{scenario_name} does not support algorithm "
            f"{CollectiveAlgorithm(algorithm).name}; valid: "
            f"{sorted(a.name for a in valid)}")


class ErrorCode(enum.IntFlag):
    """Errors raised by execution engines; OR-able like the reference's.

    Parity: reference error codes (ccl_offload_control.h:123-151 — 27 codes
    covering DMA/packetizer/arith/compression mismatch, timeouts, spare
    buffer problems). Ours cover the equivalent failure surface of the
    TPU/emulator engines.
    """

    COLLECTIVE_OP_SUCCESS = 0
    DMA_MISMATCH_ERROR = 1 << 0
    DMA_TRANSACTION_ERROR = 1 << 1
    ARITH_ERROR = 1 << 2
    PACK_TIMEOUT_STS_ERROR = 1 << 3
    PACK_SEQ_NUMBER_ERROR = 1 << 4
    COMPRESSION_ERROR = 1 << 5
    KRNL_TIMEOUT_STS_ERROR = 1 << 6
    KRNL_STS_COUNT_ERROR = 1 << 7
    RECEIVE_TIMEOUT_ERROR = 1 << 8
    RECEIVE_OFFCHIP_SPARE_BUFF_ID_NOT_VALID = 1 << 9
    RECEIVE_SPARE_BUFF_STATUS_ERROR = 1 << 10
    RECEIVE_SPARE_BUFF_DMA_TAG_MISMATCH = 1 << 11
    DMA_SIZE_ERROR = 1 << 12
    OPEN_PORT_NOT_SUCCEEDED = 1 << 13
    OPEN_CON_NOT_SUCCEEDED = 1 << 14
    COMM_NOT_CONFIGURED = 1 << 15
    ARITHCFG_NOT_CONFIGURED = 1 << 16
    COMPRESSION_NOT_SUPPORTED = 1 << 17
    STREAM_NOT_SUPPORTED = 1 << 18
    COLLECTIVE_NOT_IMPLEMENTED = 1 << 19
    RECEIVE_OFFCHIP_SPARE_BUFF_OVERFLOW = 1 << 20
    CONNECTION_CLOSED = 1 << 21
    DEVICE_NOT_READY = 1 << 22
    INVALID_CALL = 1 << 23
    # a deferred MSG_WAIT asked about a call id so old that BOTH its
    # status entry and (if it failed) its failed-calls record aged out of
    # the daemons' bounded maps: FIFO retirement proves the call retired,
    # but its outcome is genuinely unknowable — saying so beats the
    # false-success 0 the eviction used to fabricate
    CALL_OUTCOME_UNKNOWN = 1 << 24
    # multi-tenant service (accl_tpu/service): an eager-ingress message
    # was dropped because its TENANT's rx-pool reservation (plus the
    # shared overflow pool) was exhausted — typed backpressure, distinct
    # from the pool-physically-full overflow above so a noisy neighbor
    # hitting its quota is diagnosable from the error word alone, and
    # never misread as a deadline/DMA failure
    TENANT_QUOTA_EXCEEDED = 1 << 25
    # reliability layer (emulator/reliability.py): a lossy transport
    # (UDP deliver-queue overflow with retransmission disabled) dropped a
    # frame AFTER it left the wire — latched per comm AT DROP TIME so the
    # failure surfaces as itself instead of as the receiver's generic
    # recv deadline much later
    FABRIC_QUEUE_OVERFLOW = 1 << 26
    # membership (heartbeats / retransmit give-up): a connected peer
    # stopped answering — missed-heartbeat budget exhausted, or every
    # retransmission of a frame toward it went unacknowledged. Latched
    # per comm (never across tenants); the application rebuilds with
    # comm.revoke() + ACCL.shrink_communicator(dead_ranks)
    PEER_FAILED = 1 << 27
    # driver call-level retry: the retry policy re-executed the call and
    # every attempt failed — OR-ed over the final attempt's word so the
    # caller sees both WHAT kept failing and THAT retries ran out
    CALL_RETRIES_EXHAUSTED = 1 << 28
    # one-sided RMA (accl_tpu/rma): the put/get targeted a window id the
    # target rank has not registered, or the (offset, count) range falls
    # outside the registered region — typed so a mis-exchanged window id
    # fails fast at the initiator instead of as a receive timeout
    RMA_WINDOW_ERROR = 1 << 29
    # elastic membership (ACCL.grow_communicator): the join handshake
    # did not complete — a joiner died (or never started) mid-handshake,
    # or a peer is growing a DIFFERENT membership for the same comm id.
    # Transient by nature (a joiner may still be booting), so retry
    # policies treat it as retryable — unlike PEER_FAILED, which names a
    # peer that was alive and stopped answering
    JOIN_FAILED = 1 << 30
    # end-to-end data integrity (PR 13): a payload failed its checksum
    # with RECOVERY disabled (wire corruption surfacing as itself at
    # retx_window=0 instead of as a silent wrong result or a generic
    # recv deadline), a cross-rank result-fingerprint exchange
    # disagreed (ACCL(verify_integrity=...) — local combine/scratch/
    # memory corruption retransmission cannot catch), or a checkpoint's
    # content checksum failed at load (utils/checkpoint.py). NEVER
    # blind-retryable: with retransmission armed, wire corruption
    # self-heals invisibly, so this word reaching the application means
    # the data itself — not the transport — is suspect
    DATA_INTEGRITY_ERROR = 1 << 31


class StackType(enum.IntEnum):
    """Transport fabric selector.

    Parity: reference selects UDP vs TCP Vitis stacks at runtime
    (accl.py:383-395, HOUSEKEEP_SET_STACK_TYPE). TPU-native fabrics:
    in-process loopback, socket fabric (emulator tier), ICI mesh, DCN
    between slices.
    """

    LOOPBACK = 0
    SOCKET = 1  # emulator-tier framed-TCP fabric (reference: ZMQ pub/sub "wire")
    ICI = 2     # single-slice XLA collectives
    DCN = 3     # multi-slice / multi-host


class ACCLError(Exception):
    """Host-side exception carrying the OR-ed device error word.

    Parity: reference ``check_return_value`` raises on nonzero retcode
    (accl.py:617-624).
    """

    def __init__(self, error_word: int, context: str = ""):
        self.error_word = int(error_word)
        self.errors = decode_error(error_word)
        names = " | ".join(e.name for e in self.errors) or hex(self.error_word)
        super().__init__(f"ACCL call failed{' in ' + context if context else ''}: {names}")


def decode_error(error_word: int) -> list[ErrorCode]:
    """Split an OR-ed error word into its individual error codes."""
    return [e for e in ErrorCode if e != ErrorCode.COLLECTIVE_OP_SUCCESS
            and error_word & e.value]


# Default sizing knobs; parity with reference constants
# (ccl_offload_control.h:50-55): max pkt 1536B, 1MiB segments, 8MiB DMA BTT.
DEFAULT_MAX_SEGMENT_SIZE = 1 << 20          # 1 MiB, like MAX_SEG_SIZE
DEFAULT_RX_BUFFER_SIZE = 64 << 10           # spare rx buffer bytes
DEFAULT_RX_BUFFER_COUNT = 16
DEFAULT_TIMEOUT_S = 30.0
# In-flight window depth of the pipelined move executor (reference: the
# dma_mover keeps multiple moves in flight across its 11 stages). 0
# disables pipelining (strict serial retirement). Overridable per process
# via $ACCL_TPU_PIPELINE_WINDOW.
DEFAULT_PIPELINE_WINDOW = 8
# Ceiling on the segment-streamed executor's EXTRA combine workers when
# auto-sizing from cpu count: min(cap, max(0, cpus - 2)) — the scheduler
# thread executes ready moves itself, so the pool adds lanes only when
# cores exist beyond it. Override the pool size directly via
# $ACCL_TPU_COMBINE_WORKERS; $ACCL_TPU_SEGMENT_STREAM=0 falls back to
# the send-only window engine.
DEFAULT_COMBINE_WORKERS_CAP = 4
# Cross-call pipelining: how many chained streamed programs may be
# admitted to the executor concurrently (the call being drained plus the
# successors overlapping it). Bounded because every in-flight program
# parks its not-yet-consumed inbound messages in the finite rx buffer
# pool — deep chains on large worlds would overflow eager ingress.
# $ACCL_TPU_CALL_CHAIN_DEPTH overrides; devices read the env at
# CONSTRUCTION time (not import), so it can be set after importing.
DEFAULT_CALL_CHAIN_DEPTH = 2
# Multi-tenant service (accl_tpu/service): per-tenant admitted-program
# depth — same rx-pool-pressure rationale as the chain depth above, but
# scoped per tenant so one tenant's deep pipeline cannot consume every
# in-flight slot. $ACCL_TPU_TENANT_DEPTH overrides per process;
# ServiceConfig.tenant(depth=...) overrides per tenant.
DEFAULT_TENANT_DEPTH = 2
# Reliability layer (emulator/reliability.py): per-link selective-
# retransmission in-flight window, in frames. The sender keeps at most
# this many unacknowledged frames per (dst, comm) channel and
# retransmits on RTO with exponential backoff + jitter; receivers dedup
# by exact seqn and acknowledge cumulatively+selectively. 0 disables
# retransmission entirely (the pre-retransmit behavior: a lost frame
# surfaces as a typed drop latch or a recv deadline downstream).
# $ACCL_TPU_RETX_WINDOW overrides per process, read at fabric
# CONSTRUCTION time.
DEFAULT_RETX_WINDOW = 64
DEFAULT_RETX_RTO_S = 0.05      # base retransmit timeout (doubles per try)
DEFAULT_RETX_RTO_MAX_S = 1.0   # backoff ceiling
DEFAULT_RETX_MAX_TRIES = 10    # give-up bound -> PEER_FAILED latch
# Heartbeat-based peer-failure detection: interval in ms (0 = off, the
# default — heartbeats are armed explicitly per world or via
# $ACCL_TPU_HEARTBEAT_MS for daemons) and the missed-beat budget after
# which a silent peer is declared dead (PEER_FAILED latched per comm).
DEFAULT_HEARTBEAT_MS = 0
DEFAULT_HEARTBEAT_BUDGET = 3
# One-sided RMA (accl_tpu/rma): wire-size threshold below which a put
# takes the EAGER path (one control+payload frame riding the target's rx
# pool and tenant quotas, like any eager-ingress message); at or above
# it the transfer rendezvouses — RTS/CTS control frames, then payload
# segments streamed DIRECTLY into the registered window, never consuming
# rx-pool buffers (the tested invariant: a multi-MiB KV-cache push must
# not starve the pool that collectives depend on). Clamped to the
# target's rx buffer size at use. $ACCL_TPU_RMA_EAGER_MAX overrides.
DEFAULT_RMA_EAGER_MAX = 16 << 10
# control-retry cadence of the RMA engine (RTS awaiting CTS, DONE
# awaiting FIN, GET awaiting segments): base timeout doubles per try up
# to the give-up bound, then the transfer fails typed
# (RECEIVE_TIMEOUT_ERROR) instead of hanging
DEFAULT_RMA_RTO_S = 0.05
DEFAULT_RMA_MAX_TRIES = 10
TAG_ANY = 0xFFFFFFFF                        # reference uses tag=ANY sentinel
