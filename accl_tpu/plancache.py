"""Compiled-plan cache: relocatable move programs + cached streamed plans.

PRs 2-4 made the dataplane fast; what remains on small, repeated
collectives is pure Python control-plane work re-done on EVERY call:
``expand_call`` rebuilds the whole move program (segment loops, operand
dataclasses, compression flag logic) and the streamed executor re-derives
the dependency/fusion plan. A training step loop issues the *same call
shape* thousands of times — ACCL+ (arXiv:2312.11742) amortizes exactly
this with host-side ``call_chain`` pipelining over a firmware that
re-decodes nothing it doesn't have to, and NCCL-style stacks cache
compiled plans per (op, comm, size) for the same reason.

This module provides both halves of the fix:

* :class:`CompiledPlan` — a move program expanded ONCE against symbolic
  base addresses (widely-separated sentinel bases for addr_0/1/2), plus
  the streamed executor's :class:`~.emulator.executor.PlanSkeleton`
  (dependency edges, cut-through fusion, per-peer seqn DELTAS). Every
  address an expansion produces is affine in exactly one buffer base
  (``base + offset``), so :meth:`CompiledPlan.bind` relocates the whole
  program onto concrete buffers by rebasing each operand — bit-identical
  to a fresh expansion at those addresses (scripts/check_blocking.py and
  tests/test_plan_cache.py enforce this differentially).
* :class:`PlanCache` — a bounded LRU keyed on every descriptor field that
  shapes the expansion: (scenario, CONCRETE algorithm, count, dtype pair,
  communicator identity + epoch, compression/stream flags, root, func,
  tag, the zero/aliasing pattern of the three addresses, segment size).
  A hit only rebinds addresses and rebases wire seqns — no re-expansion,
  no re-planning. Entries are invalidated on communicator
  reconfiguration (the owner bumps its comm epoch AND clears) and on
  tuner re-resolution (``Tuner.refresh``/``pin`` notify registered
  caches — an epsilon-greedy or EWMA algorithm switch must never serve a
  stale plan; the concrete-algorithm key already separates entries, the
  clear keeps the table from accumulating dead ones).

``$ACCL_TPU_PLAN_CACHE=0`` disables caching process-wide (every call
takes the fresh-expansion path — the before-side of the
``benchmarks/driver_overhead.py`` plan-cache ladder);
``$ACCL_TPU_PLAN_CACHE_CAPACITY`` bounds entries per cache (default 256).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import OrderedDict

from .arith import ArithConfig
from .constants import (CCLOp, CollectiveAlgorithm, Compression, ReduceFunc,
                        StreamFlags, TAG_ANY)
from .moveengine import (Move, MoveContext, MoveMode, expand_call,
                         resolve_algorithm)

__all__ = ["CompiledPlan", "PlanCache", "cached_program", "compile_plan",
           "plan_key"]

# Symbolic base addresses: bases live at multiples of 2^44, offsets below.
# Any real expansion offset (bounded by buffer sizes — terabytes at most)
# decodes unambiguously to (which base, byte delta).
_SHIFT = 44
_BASE = 1 << _SHIFT


def _sentinel_bases(bases: tuple[int, int, int]) -> tuple[int, int, int]:
    """Symbolic stand-ins for (addr_0, addr_1, addr_2). Zero bases stay
    zero — expansions branch on address ZERO-ness (reduce_scatter's
    scratch presence, reduce TREE's accumulator check), so the symbolic
    expansion must see the same pattern the concrete one would."""
    return tuple((i + 1) << _SHIFT if b else 0
                 for i, b in enumerate(bases))  # type: ignore[return-value]


class CompiledPlan:
    """One relocatable compiled call: symbolic move program + streamed
    plan skeleton + precomputed rebinding table.

    ``bind(bases)`` returns the program relocated onto concrete buffer
    bases. Moves with no symbolic operand are shared (Move objects are
    read-only during execution); rebindings construct fresh Operand/Move
    objects, never mutating cached state — so a freed-and-reallocated
    buffer can never alias a stale address through the cache. A small
    per-plan memo makes the steady state (same buffers every step) a
    dictionary hit."""

    __slots__ = ("skeleton", "plan_us", "_moves_sym", "_rebinds", "_memo")

    _MEMO_SLOTS = 4  # double-buffered training loops alternate 2 bindings

    def __init__(self, moves_sym: list[Move], skeleton, plan_us: float):
        self.skeleton = skeleton
        self.plan_us = plan_us
        self._moves_sym = moves_sym
        self._rebinds: list[tuple | None] = []
        for mv in moves_sym:
            rb = []
            for slot in ("op0", "op1", "res"):
                op = getattr(mv, slot)
                if op.mode is MoveMode.IMMEDIATE and op.addr >= _BASE:
                    idx = (op.addr >> _SHIFT) - 1
                    delta = op.addr - ((idx + 1) << _SHIFT)
                    if idx not in (0, 1, 2):
                        # only reachable for an offset overflowing the
                        # LAST sentinel's decode range; overflow from an
                        # earlier base is excluded by compile_plan's
                        # extent bound, which is the real guard
                        raise ValueError(
                            f"unrelocatable operand address {op.addr:#x}")
                    rb.append((slot, idx, delta))
            self._rebinds.append(tuple(rb) if rb else None)
        self._memo: OrderedDict[tuple, list[Move]] = OrderedDict()

    def bind(self, bases: tuple[int, int, int]) -> list[Move]:
        """Relocate the program onto concrete (addr_0, addr_1, addr_2)."""
        key = tuple(bases)
        got = self._memo.get(key)
        if got is not None:
            self._memo.move_to_end(key)
            return got
        moves: list[Move] = []
        for mv, rb in zip(self._moves_sym, self._rebinds):
            if rb is None:
                moves.append(mv)
                continue
            kw = {}
            for slot, idx, delta in rb:
                op = getattr(mv, slot)
                kw[slot] = dataclasses.replace(op, addr=bases[idx] + delta)
            moves.append(dataclasses.replace(mv, **kw))
        if len(self._memo) >= self._MEMO_SLOTS:
            self._memo.popitem(last=False)
        self._memo[key] = moves
        return moves


def compile_plan(*, scenario: CCLOp, count: int, world_size: int,
                 local_rank: int, arithcfg: ArithConfig,
                 max_segment_size: int, root_src_dst: int = 0,
                 func: ReduceFunc = ReduceFunc.SUM, tag: int = TAG_ANY,
                 bases: tuple[int, int, int] = (0, 0, 0),
                 compression: Compression = Compression.NONE,
                 stream: StreamFlags = StreamFlags.NO_STREAM,
                 algorithm: CollectiveAlgorithm = CollectiveAlgorithm.AUTO,
                 streamed: bool = True, counts=None) -> CompiledPlan:
    """Expand one call against symbolic bases and derive its streamed plan
    skeleton. ``algorithm`` must already be CONCRETE for ops with an
    algorithm axis (see :func:`~.moveengine.resolve_algorithm`) — the
    symbolic context carries no tuner. ``streamed=False`` (serial/window
    executors) skips the skeleton."""
    # relocation-safety bound: no expansion addresses beyond
    # (world_size + 2) x count elements past any base (the widest layout
    # is a W-chunk vector plus tail slack), so requiring that extent to
    # fit the 2^44 sentinel spacing guarantees every symbolic address
    # decodes to the base it came from — an offset can never cross into
    # the next sentinel's range
    extent = (world_size + 2) * count * arithcfg.uncompressed_elem_bytes
    if extent >= _BASE:
        raise ValueError(
            f"call too large for symbolic relocation "
            f"({extent} bytes per base vs {_BASE} spacing); "
            f"disable the plan cache ($ACCL_TPU_PLAN_CACHE=0)")
    ctx = MoveContext(world_size=world_size, local_rank=local_rank,
                      arithcfg=arithcfg, max_segment_size=max_segment_size)
    sym = _sentinel_bases(bases)
    moves = expand_call(ctx, scenario, count=count,
                        root_src_dst=root_src_dst, func=func, tag=tag,
                        addr_0=sym[0], addr_1=sym[1], addr_2=sym[2],
                        compression=compression, stream=stream,
                        algorithm=algorithm, counts=counts)
    t0 = time.perf_counter()
    skeleton = None
    if streamed:
        from .emulator.executor import plan_skeleton
        skeleton = plan_skeleton(moves)
    plan_us = (time.perf_counter() - t0) * 1e6
    return CompiledPlan(moves, skeleton, plan_us)


def plan_key(*, scenario: CCLOp, algorithm: CollectiveAlgorithm, count: int,
             arithcfg: ArithConfig, comm_id: int, world_size: int,
             local_rank: int, comm_epoch: int, compression: Compression,
             stream: StreamFlags, root_src_dst: int, func: ReduceFunc,
             tag: int, bases: tuple[int, int, int], max_segment_size: int,
             streamed: bool, counts=None) -> tuple:
    """Cache key: every input that shapes the expansion or its plan.
    ``algorithm`` must be the CONCRETE algorithm the call will run (tuner
    re-resolution then lands on a different key). The three addresses
    enter only through their zero-ness (expansions branch on it) and
    aliasing pattern — concrete values are relocation inputs, not plan
    shape. ``counts`` (alltoallv) is the count-vector SIGNATURE: every
    entry shapes offsets, lanes and zero-peer skipping, so the full pair
    of tuples enters the key — two uneven exchanges share a plan exactly
    when their vectors match element-for-element."""
    a0, a1, a2 = bases
    csig = None if counts is None else (tuple(int(c) for c in counts[0]),
                                        tuple(int(c) for c in counts[1]))
    return (int(scenario), int(algorithm), int(count),
            arithcfg.uncompressed_dtype.name, arithcfg.compressed_dtype.name,
            int(comm_id), int(world_size), int(local_rank), int(comm_epoch),
            int(compression), int(stream), int(root_src_dst), int(func),
            int(tag),
            bool(a0), bool(a1), bool(a2),          # zero pattern
            a1 == a0, a2 == a0, a2 == a1,          # in-place aliasing
            int(max_segment_size), bool(streamed), csig)


def cached_program(cache: "PlanCache", *, scenario: CCLOp, count: int,
                  world_size: int, local_rank: int, arithcfg: ArithConfig,
                  max_segment_size: int, comm_id: int, comm_epoch: int,
                  root_src_dst: int = 0,
                  func: ReduceFunc = ReduceFunc.SUM, tag: int = TAG_ANY,
                  bases: tuple[int, int, int] = (0, 0, 0),
                  compression: Compression = Compression.NONE,
                  stream: StreamFlags = StreamFlags.NO_STREAM,
                  algorithm: CollectiveAlgorithm = CollectiveAlgorithm.AUTO,
                  tuner=None, streamed: bool = True,
                  compile_missing: bool = True, tenant: str = "",
                  counts=None):
    """The one program-preparation path shared by every tier (emu device,
    rank daemon, chained admission): resolve AUTO to the CONCRETE
    algorithm BEFORE building the key (the invariant that makes tuner
    re-resolution staleness-proof), look up, optionally compile+store on
    a miss, and relocate onto ``bases``. A disabled cache takes the
    fresh-expansion path here too, so cache-on and cache-off runs can
    never expand through drifting argument lists.

    Returns ``(moves, skeleton, state, expand_us, plan_us)`` — state
    "hit"|"miss"|"bypass"; ``expand_us`` covers expansion + relocation
    (relocation only on a hit), ``plan_us`` the streamed-skeleton
    derivation (0.0 on a hit — the cached skeleton is reused); the two
    are disjoint. With ``compile_missing=False`` a miss returns ``None``
    instead of compiling (the chained-admission gate: a miss pays
    expansion anyway, so it takes the ordinary path — which accounts its
    own lookup, so a chained miss counts twice in ``misses``). The
    lookup is a single atomic cache access: a concurrent invalidation
    can only turn a would-be hit into an honest miss."""
    t0 = time.perf_counter()
    if not cache.enabled:
        cache.note_bypass()
        ctx = MoveContext(world_size=world_size, local_rank=local_rank,
                          arithcfg=arithcfg,
                          max_segment_size=max_segment_size, tuner=tuner)
        moves = expand_call(ctx, scenario, count=count,
                            root_src_dst=root_src_dst, func=func, tag=tag,
                            addr_0=bases[0], addr_1=bases[1],
                            addr_2=bases[2], compression=compression,
                            stream=stream, algorithm=algorithm,
                            counts=counts)
        t1 = time.perf_counter()
        skeleton = None
        if streamed:
            from .emulator.executor import plan_skeleton
            skeleton = plan_skeleton(moves)
        return (moves, skeleton, "bypass", (t1 - t0) * 1e6,
                (time.perf_counter() - t1) * 1e6)
    alg = resolve_algorithm(scenario, algorithm, world_size=world_size,
                            count=count,
                            elem_bytes=arithcfg.uncompressed_elem_bytes,
                            tuner=tuner, addr_1=bases[1])
    key = plan_key(scenario=scenario, algorithm=alg, count=count,
                   arithcfg=arithcfg, comm_id=comm_id,
                   world_size=world_size, local_rank=local_rank,
                   comm_epoch=comm_epoch, compression=compression,
                   stream=stream, root_src_dst=root_src_dst, func=func,
                   tag=tag, bases=bases,
                   max_segment_size=max_segment_size, streamed=streamed,
                   counts=counts)
    plan = cache.lookup(key)
    state, plan_us = "hit", 0.0
    if plan is None:
        if not compile_missing:
            return None
        state = "miss"
        plan = compile_plan(scenario=scenario, count=count,
                            world_size=world_size, local_rank=local_rank,
                            arithcfg=arithcfg,
                            max_segment_size=max_segment_size,
                            root_src_dst=root_src_dst, func=func, tag=tag,
                            bases=bases, compression=compression,
                            stream=stream, algorithm=alg,
                            streamed=streamed, counts=counts)
        plan_us = plan.plan_us
        cache.store(key, plan, tenant=tenant)
    moves = plan.bind(bases)
    expand_us = max(0.0, (time.perf_counter() - t0) * 1e6 - plan_us)
    return moves, plan.skeleton, state, expand_us, plan_us


class PlanCache:
    """Bounded LRU of :class:`CompiledPlan` with observability counters.

    Thread-safe (the owning device's call worker is the main user, but
    tuner invalidation listeners fire from arbitrary threads). Counters:
    ``hits``/``misses``/``bypasses`` per lookup outcome, ``evictions``
    for capacity pressure, ``invalidations`` per reason ("comm", "tuner",
    ...) — surfaced through the driver (``ACCL.plan_cache_stats``) and
    the tuner (``Tuner.plan_cache_stats``) so epsilon-greedy exploration
    cost is observable."""

    def __init__(self, enabled: bool | None = None,
                 capacity: int | None = None):
        if enabled is None:
            enabled = os.environ.get("ACCL_TPU_PLAN_CACHE", "1").lower() \
                not in ("0", "false", "off", "")
        if capacity is None:
            capacity = int(os.environ.get("ACCL_TPU_PLAN_CACHE_CAPACITY",
                                          256))
        self.enabled = bool(enabled)
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, CompiledPlan] = OrderedDict()
        # multi-tenant fairness: entry -> tenant attribution plus live
        # per-tenant entry counts. The LRU is shared per device/daemon,
        # so N tenants' shapes would evict each other blindly; eviction
        # skips tenants at/below their MINIMUM SHARE (capacity / live
        # tenants) while any tenant sits above its share — a shape-heavy
        # tenant evicts its own coldest entries before touching a small
        # tenant's working set.
        self._tenant_of: dict[tuple, str] = {}
        self.tenant_entries: dict[str, int] = {}
        self.hits = 0
        self.misses = 0
        self.bypasses = 0
        self.evictions = 0
        self.invalidations: dict[str, int] = {}

    def lookup(self, key: tuple) -> CompiledPlan | None:
        with self._lock:
            plan = self._entries.get(key)
            if plan is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return plan

    def _account_locked(self, key: tuple, tenant: str):
        old = self._tenant_of.get(key)
        if old == tenant:
            return
        if old is not None:
            self._dec_tenant_locked(key)
        self._tenant_of[key] = tenant
        self.tenant_entries[tenant] = \
            self.tenant_entries.get(tenant, 0) + 1

    def _dec_tenant_locked(self, key: tuple):
        t = self._tenant_of.pop(key, None)
        if t is None:
            return
        n = self.tenant_entries.get(t, 0) - 1
        if n > 0:
            self.tenant_entries[t] = n
        else:
            self.tenant_entries.pop(t, None)

    def _evict_one_locked(self):
        """Capacity eviction with a minimum-share floor: walk from the
        LRU end, skipping entries whose tenant holds no more than
        capacity / live-tenants entries — as long as SOME tenant is over
        its share (there always is when the cache is over capacity with
        a protected tenant skipped). Falls back to plain LRU when every
        tenant is within share (single-tenant caches take this branch
        with zero extra work)."""
        n_tenants = max(1, len(self.tenant_entries))
        min_share = self.capacity // n_tenants
        victim = None
        if n_tenants > 1:
            for key in self._entries:          # LRU -> MRU order
                t = self._tenant_of.get(key, "")
                if self.tenant_entries.get(t, 0) > min_share:
                    victim = key
                    break
        if victim is None:
            victim, _ = self._entries.popitem(last=False)
        else:
            del self._entries[victim]
        self._dec_tenant_locked(victim)
        self.evictions += 1

    def store(self, key: tuple, plan: CompiledPlan, tenant: str = ""):
        with self._lock:
            self._entries[key] = plan
            self._entries.move_to_end(key)
            self._account_locked(key, tenant)
            while len(self._entries) > self.capacity:
                self._evict_one_locked()

    def note_bypass(self):
        with self._lock:
            self.bypasses += 1

    def invalidate(self, reason: str = "explicit"):
        """Drop every entry (communicator reconfiguration, tuner
        re-resolution, explicit reset)."""
        with self._lock:
            self._entries.clear()
            self._tenant_of.clear()
            self.tenant_entries.clear()
            self.invalidations[reason] = \
                self.invalidations.get(reason, 0) + 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "entries": len(self._entries),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "bypasses": self.bypasses,
                "evictions": self.evictions,
                "invalidations": dict(self.invalidations),
                "tenant_entries": dict(self.tenant_entries),
            }

    def metrics_rows(self, labels: dict):
        """This cache's counters as registry-collector rows
        (:meth:`~accl_tpu.tracing.MetricsRegistry.register_collector`
        format) — one shared mapping so the emu device and the rank
        daemon can never drift in how they report the cache."""
        st = self.stats()
        for k in ("hits", "misses", "bypasses", "evictions"):
            yield ("counter", f"plan_cache_{k}_total", labels, st[k])
        yield ("gauge", "plan_cache_entries", labels, st["entries"])
        yield ("gauge", "plan_cache_enabled", labels, int(st["enabled"]))
        for reason, n in st["invalidations"].items():
            yield ("counter", "plan_cache_invalidations_total",
                   dict(labels, reason=reason), n)
        for tenant, n in st["tenant_entries"].items():
            if tenant:  # unattributed entries have no tenant series
                yield ("gauge", "plan_cache_tenant_entries",
                       dict(labels, tenant=tenant), n)
