"""Ring attention: long-context attention with KV rotation hidden
behind the attention matmul.

The sequence is sharded over the ring: rank r holds a query block Q_r
and a KV block (K_r, V_r). Every rank computes attention of its
queries against ALL KV blocks by rotating the KV pair one hop per
step — and because softmax admits an online (streaming) formulation,
each rotated block folds into a running (max, denominator, numerator)
accumulator without ever materializing the full score matrix
("Ring Attention with Blockwise Transformers", PAPERS.md).

The overlap structure is the point: at step k the NEXT block's
rotation (async send + chained recv, double-buffered) is already in
flight while THIS block's matmuls run, so the wire time disappears
under compute for any sequence long enough that the matmul dominates.
``overlap=False`` degrades to the serial rotate-then-compute loop —
the bench's baseline leg.

Accumulation runs in float64 regardless of the buffer dtype, so the
result matches :func:`ring_attention_reference` to float32 rtol even
though the blocks arrive in ring order rather than sequence order.
"""

from __future__ import annotations

import numpy as np

__all__ = ["ring_attention_forward", "ring_attention_reference"]


def ring_attention_reference(q: np.ndarray, k: np.ndarray,
                             v: np.ndarray) -> np.ndarray:
    """Serial oracle: plain softmax(Q K^T / sqrt(d)) V over the FULL
    key/value sequence, float64 internally."""
    q64 = q.astype(np.float64)
    k64 = k.astype(np.float64)
    v64 = v.astype(np.float64)
    s = (q64 @ k64.T) / np.sqrt(q.shape[-1])
    s -= s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    return ((p @ v64) / p.sum(axis=-1, keepdims=True)).astype(q.dtype)


def _fold_block(q64, kblk, vblk, m, l, acc, scale):
    """One online-softmax update: fold a KV block into the running
    (row max ``m``, denominator ``l``, numerator ``acc``)."""
    s = (q64 @ kblk.astype(np.float64).T) * scale
    m_new = np.maximum(m, s.max(axis=-1))
    corr = np.exp(m - m_new)
    p = np.exp(s - m_new[:, None])
    l[:] = l * corr + p.sum(axis=-1)
    acc[:] = acc * corr[:, None] + p @ vblk.astype(np.float64)
    m[:] = m_new


def ring_attention_forward(a, q: np.ndarray, k: np.ndarray,
                           v: np.ndarray, *, comm=None,
                           compress_dtype=None,
                           block_scale: bool | int = False,
                           overlap: bool = True, use_chain: bool = True,
                           meter=None):
    """Forward pass of ring attention on driver ``a``.

    ``q``/``k``/``v`` are this rank's blocks, shape (block_len, d) —
    every rank's KV block must have the SAME shape (the rotation is a
    fixed-size exchange; uneven sequence shards belong to
    :func:`accl_tpu.workloads.moe`-style alltoallv routing). Returns
    ``(out, stats)``: the attention output for the local queries and
    the meter's stats dict (``overlap_frac`` et al.).

    Rotation protocol per step: pack (K, V) in one buffer, async-send
    it to the next ring neighbour and post the paired recv CHAINED
    behind it (``chain=True`` — the device admits the recv while the
    send drains, no host round trip on the rotation's critical path),
    then run the attention matmul on the CURRENT block. The sends are
    eager, so the W-cycle cannot rendezvous-deadlock. Double
    buffering makes the in-flight recv land in the buffer compute is
    NOT reading."""
    from . import OverlapMeter
    comm = comm or a.comm
    W, me = comm.size, comm.local_rank
    if k.shape != v.shape or k.ndim != 2 or q.ndim != 2 \
            or q.shape[1] != k.shape[1]:
        raise ValueError(
            f"q/k/v must be (block_len, d) with one d: got q "
            f"{q.shape}, k {k.shape}, v {v.shape}")
    lkv, d = k.shape
    scale = 1.0 / np.sqrt(d)
    meter = meter if meter is not None else OverlapMeter()

    q64 = q.astype(np.float64)
    m = np.full(q.shape[0], -np.inf)
    l = np.zeros(q.shape[0])
    acc = np.zeros((q.shape[0], d))

    if W == 1:
        _fold_block(q64, k, v, m, l, acc, scale)
        stats = meter.publish(a.rank, "ring_attention", steps=1)
        return (acc / l[:, None]).astype(q.dtype), stats

    n = 2 * lkv * d
    cur = a.buffer((n,), np.float32)
    nxt = a.buffer((n,), np.float32)
    cur.data[:lkv * d] = k.astype(np.float32).ravel()
    cur.data[lkv * d:] = v.astype(np.float32).ravel()
    nxt_rank = (me + 1) % W
    prv_rank = (me - 1) % W

    for step in range(W):
        inflight = None
        if step < W - 1:
            # rotate BEFORE computing: the pair is on the wire for the
            # whole matmul below. Tag by step so a slow rank's frame
            # cannot be claimed by the next step's TAG_ANY recv.
            hs = a.send(cur, n, nxt_rank, tag=step, comm=comm,
                        compress_dtype=compress_dtype,
                        block_scale=block_scale, run_async=True)
            hr = a.recv(nxt, n, prv_rank, tag=step, comm=comm,
                        compress_dtype=compress_dtype,
                        block_scale=block_scale, run_async=True,
                        chain=use_chain)
            meter.issue(hs)
            meter.issue(hr)
            inflight = (hs, hr)
            if not overlap:
                # serial baseline: expose the whole rotation
                meter.wait(hs)
                meter.wait(hr)
        kblk = cur.data[:lkv * d].reshape(lkv, d)
        vblk = cur.data[lkv * d:].reshape(lkv, d)
        _fold_block(q64, kblk, vblk, m, l, acc, scale)
        if inflight is not None:
            if overlap:
                for h in inflight:
                    meter.wait(h)
            cur, nxt = nxt, cur

    stats = meter.publish(a.rank, "ring_attention", steps=W)
    return (acc / l[:, None]).astype(q.dtype), stats
