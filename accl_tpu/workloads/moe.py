"""Expert-parallel MoE dispatch/combine on ``alltoallv``.

Top-1 routing assigns every local token a destination expert rank;
real routers are SKEWED (hot experts draw multiples of the even
share), so the exchange is exactly the variable-count collective:
tokens grouped by destination form the send count vector, the peers'
group sizes form the recv vector, and zero-count peers fall out of
the wire entirely. The protocol is the MPI idiom:

1. one fixed-count ``alltoall`` of the per-peer token counts (how
   much each peer will send me);
2. ``alltoallv`` DISPATCH of the grouped tokens (optionally fp8
   block-scaled — activations tolerate the quantized wire, and the
   skewed chunks requantize in flight like any other collective);
3. local expert compute on whatever landed;
4. ``alltoallv`` COMBINE with the mirrored count vectors, landing
   expert outputs back where their tokens came from.

Communication hides behind compute by MICROBATCHING: the token set
splits into chunks, chunk c+1's dispatch and chunk c's combine are
in flight while chunk c's expert matmul runs. Every rank derives the
chunk split from the count vectors alone (same floor-division
boundaries), so the per-chunk vectors stay pairwise consistent
without another exchange. ``overlap=False`` is the serial baseline
leg for the bench.
"""

from __future__ import annotations

import numpy as np

__all__ = ["moe_dispatch_combine", "moe_reference", "default_expert"]


def default_expert(rank: int, d: int):
    """Deterministic per-rank expert: tanh(x W + b) with weights from
    a rank-seeded generator, so oracle and engine agree bit-for-bit on
    what expert r computes."""
    rng = np.random.default_rng(1000 + rank)
    w = rng.standard_normal((d, d)).astype(np.float32) / np.sqrt(d)
    b = rng.standard_normal(d).astype(np.float32) * 0.1

    def f(x: np.ndarray) -> np.ndarray:
        return np.tanh(x @ w + b)
    return f


def moe_reference(tokens, dest, expert_fns):
    """Serial oracle: ``tokens``/``dest`` are per-rank lists (tokens[r]
    is rank r's (T_r, d) array, dest[r] its (T_r,) destination rank
    vector); returns the per-rank combined outputs in original token
    order — each token transformed by its destination rank's expert."""
    out = []
    for toks, dst in zip(tokens, dest):
        y = np.empty_like(toks)
        for r in np.unique(dst):
            sel = dst == r
            y[sel] = expert_fns[int(r)](toks[sel])
        out.append(y)
    return out


def _chunk_split(counts: tuple[int, ...], n_chunks: int):
    """Split a count vector into ``n_chunks`` per-chunk vectors with
    floor-division boundaries (chunk c of a count-n segment is
    [n*c//K, n*(c+1)//K)). Pure arithmetic on the vector, so sender
    and receiver derive identical splits from their mirrored counts."""
    return [tuple(c * (ci + 1) // n_chunks - c * ci // n_chunks
                  for c in counts)
            for ci in range(n_chunks)]


def moe_dispatch_combine(a, tokens: np.ndarray, dest: np.ndarray, *,
                         comm=None, expert_fn=None, n_chunks: int = 2,
                         compress_dtype=None,
                         block_scale: bool | int = False,
                         overlap: bool = True, meter=None):
    """Dispatch local ``tokens`` (T, d) to their ``dest`` ranks over
    ``alltoallv``, run this rank's expert on what lands, combine the
    outputs back. Returns ``(out, stats)`` with ``out`` in the
    ORIGINAL local token order and ``stats`` the overlap ledger.

    ``compress_dtype``/``block_scale`` apply to the DISPATCH leg only
    (activations on the quantized wire); the combine returns expert
    outputs at full precision. ``expert_fn`` defaults to this rank's
    :func:`default_expert`."""
    from . import OverlapMeter
    comm = comm or a.comm
    W, me = comm.size, comm.local_rank
    if tokens.ndim != 2:
        raise ValueError(f"tokens must be (T, d); got {tokens.shape}")
    t_total, d = tokens.shape
    dest = np.asarray(dest, dtype=np.int64)
    if dest.shape != (t_total,):
        raise ValueError(
            f"dest must be one rank per token; got {dest.shape} for "
            f"{t_total} tokens")
    if t_total and (dest.min() < 0 or dest.max() >= W):
        raise ValueError("dest ranks out of range")
    expert_fn = expert_fn or default_expert(me, d)
    meter = meter if meter is not None else OverlapMeter()
    n_chunks = max(1, min(n_chunks, max(1, t_total)))

    # group tokens by destination (stable, so the combine un-permutes)
    order = np.argsort(dest, kind="stable")
    send_tok = np.ascontiguousarray(tokens[order], dtype=np.float32)
    send_counts = tuple(int(c) for c in np.bincount(dest, minlength=W))

    # 1) count exchange: one fixed-count alltoall of the vectors
    cnt_src = a.buffer((W,), np.int64)
    cnt_dst = a.buffer((W,), np.int64)
    cnt_src.data[:] = send_counts
    a.alltoall(cnt_src, cnt_dst, 1, comm=comm)
    recv_counts = tuple(int(c) for c in cnt_dst.data)
    t_recv = sum(recv_counts)

    send_chunks = _chunk_split(send_counts, n_chunks)
    recv_chunks = _chunk_split(recv_counts, n_chunks)

    # staging: per chunk, the grouped tokens bound for each peer are a
    # GATHER from the sorted array (chunk c takes slice c of EVERY
    # peer segment — not contiguous), packed host-side into the
    # chunk's own buffers so all chunks can be in flight at once
    soff = np.concatenate(([0], np.cumsum(send_counts)))
    roff = np.concatenate(([0], np.cumsum(recv_counts)))
    disp_src, disp_dst, comb_src, comb_dst = [], [], [], []
    for ci in range(n_chunks):
        ns = sum(send_chunks[ci])
        nr = sum(recv_chunks[ci])
        disp_src.append(a.buffer((max(1, ns * d),), np.float32))
        disp_dst.append(a.buffer((max(1, nr * d),), np.float32))
        comb_src.append(a.buffer((max(1, nr * d),), np.float32))
        comb_dst.append(a.buffer((max(1, ns * d),), np.float32))
        rows = np.concatenate([
            np.arange(soff[p] + send_counts[p] * ci // n_chunks,
                      soff[p] + send_counts[p] * (ci + 1) // n_chunks)
            for p in range(W)]) if ns else np.empty(0, np.int64)
        if ns:
            disp_src[ci].data[:ns * d] = send_tok[rows].ravel()

    def _vec(counts, scale):
        return tuple(c * scale for c in counts)

    # 2) dispatch every chunk up front: chunk c+1 is on the wire while
    #    chunk c computes (counts ride in ELEMENTS = tokens * d)
    disp_h = []
    for ci in range(n_chunks):
        h = a.alltoallv(disp_src[ci], disp_dst[ci],
                        _vec(send_chunks[ci], d), _vec(recv_chunks[ci], d),
                        comm=comm, compress_dtype=compress_dtype,
                        block_scale=block_scale, run_async=True)
        meter.issue(h)
        disp_h.append(h)
        if not overlap:
            meter.wait(h)

    # 3+4) expert compute per chunk, combine issued async right after
    # (in flight under the NEXT chunk's compute)
    comb_h = []
    for ci in range(n_chunks):
        meter.wait(disp_h[ci])
        nr = sum(recv_chunks[ci])
        if nr:
            x = disp_dst[ci].data[:nr * d].reshape(nr, d)
            comb_src[ci].data[:nr * d] = \
                expert_fn(x).astype(np.float32).ravel()
        h = a.alltoallv(comb_src[ci], comb_dst[ci],
                        _vec(recv_chunks[ci], d), _vec(send_chunks[ci], d),
                        comm=comm, run_async=True)
        meter.issue(h)
        comb_h.append(h)
        if not overlap:
            meter.wait(h)
    if overlap:
        for h in comb_h:
            meter.wait(h)

    # un-permute: chunk ci's combined rows are slice ci of every peer
    # segment of the SORTED order; scatter them back to token order
    out_sorted = np.empty((t_total, d), dtype=np.float32)
    for ci in range(n_chunks):
        ns = sum(send_chunks[ci])
        if not ns:
            continue
        rows = np.concatenate([
            np.arange(soff[p] + send_counts[p] * ci // n_chunks,
                      soff[p] + send_counts[p] * (ci + 1) // n_chunks)
            for p in range(W)])
        out_sorted[rows] = comb_dst[ci].data[:ns * d].reshape(ns, d)
    out = np.empty_like(out_sorted)
    out[order] = out_sorted

    stats = meter.publish(a.rank, "moe", steps=n_chunks)
    stats["tokens"] = t_total
    stats["recv_tokens"] = t_recv
    stats["send_counts"] = send_counts
    stats["recv_counts"] = recv_counts
    return out, stats
