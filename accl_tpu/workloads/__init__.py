"""Compute-overlapped end-to-end workloads (ROADMAP item: workload
scenarios gated on achieved overlap).

Collective microbenchmarks measure the wire in isolation; what the
paper's deployments care about is whether communication HIDES behind
model compute. This package drives two real workload shapes through
the driver's async/chained call path with host-side compute between
the calls, and measures the overlap it actually achieved:

* :mod:`~accl_tpu.workloads.ring_attention` — long-context attention
  over a ring: block k's KV rotation (send + chained recv) is in
  flight while block k-1's attention matmul runs;
* :mod:`~accl_tpu.workloads.moe` — expert-parallel MoE: skewed top-1
  routing lowered onto ``alltoallv`` dispatch/combine (the dispatch
  leg optionally fp8 block-scaled), microbatched so chunk c+1's
  dispatch and chunk c's combine ride under chunk c's expert matmul.

The measurement is the :class:`OverlapMeter`: every issued
communication handle is stamped at issue and at completion (done
callback), and the time the workload then actually BLOCKS in
``wait()`` is its exposed communication. ``overlap_frac`` = hidden /
total in-flight time — 1.0 when every transfer retired under compute,
0.0 for a fully serial issue-wait-compute loop. This is the workload-
level complement of the per-call ``CallRecord.overlap_frac`` (combine
time hidden behind wire activity, docs/OBSERVABILITY.md): that metric
sees inside one streamed collective; this one sees across the
compute/communication boundary the engine cannot observe.

``make bench-emu`` runs both workloads (benchmarks/workloads.py) and
gates on the measured overlap via ``$ACCL_BENCH_MIN_OVERLAP_FRAC``.

Metric families (registry: accl_tpu.tracing.METRICS):

* ``workload_overlap_frac`` (gauge; rank, workload) — last run's
  achieved overlap;
* ``workload_steps_total`` (counter; rank, workload) — compute steps
  driven;
* ``workload_comm_us_total`` / ``workload_exposed_us_total``
  (counters; rank, workload) — in-flight vs exposed-blocking
  communication time, the overlap ratio's raw numerator inputs.
"""

from __future__ import annotations

import time

from ..tracing import METRICS

__all__ = ["OverlapMeter", "ring_attention", "moe"]


class OverlapMeter:
    """Ledger of issued communication vs time spent blocked on it.

    Usage: ``meter.issue(handle)`` right after an async call is
    issued; ``meter.wait(handle)`` instead of ``handle.wait()`` when
    the workload needs the result. Completion instants come from the
    handle's done callback, so a transfer that retires mid-compute is
    credited its true in-flight span even though the workload only
    looks at it later."""

    def __init__(self):
        self._recs: dict[int, dict] = {}
        self.exposed_s = 0.0

    def issue(self, handle):
        rec = {"t0": time.perf_counter(), "t1": None}
        self._recs[id(handle)] = rec

        def _done(_err, r=rec):
            r["t1"] = time.perf_counter()
        handle.add_done_callback(_done)
        return handle

    def wait(self, handle):
        t0 = time.perf_counter()
        handle.wait()
        dt = time.perf_counter() - t0
        self.exposed_s += dt
        rec = self._recs.get(id(handle))
        if rec is not None and rec["t1"] is None:
            # callback raced the waiter: the wait return IS completion
            rec["t1"] = time.perf_counter()
        return dt

    @property
    def comm_s(self) -> float:
        now = time.perf_counter()
        return sum((r["t1"] if r["t1"] is not None else now) - r["t0"]
                   for r in self._recs.values())

    @property
    def overlap_frac(self) -> float:
        """Fraction of total in-flight communication hidden behind the
        workload's own compute: 1 - exposed/in-flight, clamped to
        [0, 1]. 1.0 when nothing was issued (no comm to expose)."""
        total = self.comm_s
        if total <= 0.0:
            return 1.0
        return max(0.0, min(1.0, 1.0 - self.exposed_s / total))

    def publish(self, rank: int, workload: str, steps: int) -> dict:
        """Push this run's ledger into the metrics registry and return
        the stats dict the workload hands back to its caller."""
        of = round(self.overlap_frac, 4)
        METRICS.set_gauge("workload_overlap_frac", of, rank=rank,
                          workload=workload)
        METRICS.inc("workload_steps_total", steps, rank=rank,
                    workload=workload)
        METRICS.inc("workload_comm_us_total",
                    round(self.comm_s * 1e6), rank=rank, workload=workload)
        METRICS.inc("workload_exposed_us_total",
                    round(self.exposed_s * 1e6), rank=rank,
                    workload=workload)
        return {"overlap_frac": of, "comm_s": self.comm_s,
                "exposed_s": self.exposed_s, "steps": steps}


from . import moe, ring_attention  # noqa: E402  (public submodules)
