"""Test harness helpers: spin up an N-rank emulated world in-process.

Parity: the reference test story launches N emulator processes under mpirun
and drives each from a Python test process (test/host/test_all.py). The
in-process equivalent here gives the same multi-rank semantics with threads,
for fast unit tests; the socket-daemon tier (emulator/daemon.py) covers the
true multi-process story.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
from typing import Callable, Sequence

from .accl import ACCL
from .communicator import Communicator, Rank
from .device.emu import EmuContext


def emu_world(world_size: int, nbufs: int = 16, bufsize: int | None = None,
              timeout: float = 20.0,
              max_segment_size: int | None = None,
              tuner=None, pipeline_window: int | None = None,
              segment_stream: bool | None = None,
              plan_cache: bool | None = None,
              service=None, tenant: str | None = None,
              hosts=None, inter_alpha_us: float | None = None,
              inter_beta_gbps: float | None = None,
              outer_tiers=None,
              retx_window: int | None = None,
              csum: bool | None = None,
              retry_policy=None, verify_integrity: bool = False
              ) -> list[ACCL]:
    """Create ``world_size`` ACCL instances sharing an in-process fabric.

    ``tuner`` (a single :class:`~accl_tpu.tuner.Tuner`) is shared by every
    rank — the only safe shape: all member ranks of a collective must
    resolve AUTO to the same algorithm. ``pipeline_window`` sets the
    executors' in-flight window (0 = serial reference engine);
    ``segment_stream`` selects the dependency-aware segment pipeline vs
    the send-only window (None = process default); ``plan_cache``
    enables/disables the compiled-plan cache (None = process default,
    ``$ACCL_TPU_PLAN_CACHE``). ``service`` configures the multi-tenant
    service layer (a :class:`~accl_tpu.service.ServiceConfig`, True/False,
    or None = process default, ``$ACCL_TPU_SERVICE``); ``tenant`` groups
    this driver set's communicators under one service tenant (see
    :func:`add_tenant` for attaching further tenants to the same world).
    ``hosts`` declares a two-tier grouping (rank->host id, contiguous
    runs): devices then report a MeshTopology (so a shared tuner can
    select HIERARCHICAL, accl_tpu/hier) and — with ``inter_alpha_us``/
    ``inter_beta_gbps`` — the fabric emulates the slow inter-host tier
    on every cross-host link. ``outer_tiers`` adds coarser boundaries
    (rack, pod, ...) as ``(hosts_map, alpha_us, beta_gbps)`` triples
    innermost-first: the fabric profiles them in->out (a cross-rack
    link gets the rack figures) and devices report the full N-tier
    MeshTopology."""
    kw = {"nbufs": nbufs, "pipeline_window": pipeline_window,
          "segment_stream": segment_stream, "plan_cache": plan_cache,
          "service": service, "hosts": hosts,
          "inter_alpha_us": inter_alpha_us,
          "inter_beta_gbps": inter_beta_gbps,
          "outer_tiers": outer_tiers,
          "retx_window": retx_window, "csum": csum}
    if bufsize is not None:
        kw["bufsize"] = bufsize
    ctx = EmuContext(world_size, **kw)
    accls = []
    for r in range(world_size):
        comm = Communicator(
            ranks=[Rank() for _ in range(world_size)], local_rank=r)
        accls.append(ACCL(ctx.device(r), comm, timeout=timeout,
                          max_segment_size=max_segment_size, tuner=tuner,
                          tenant=tenant, retry_policy=retry_policy,
                          verify_integrity=verify_integrity))
    return accls


def add_tenant(accls: Sequence[ACCL], tenant: str, key: int = 1,
               timeout: float = 20.0,
               max_segment_size: int | None = None,
               tuner=None) -> list[ACCL]:
    """Attach another tenant's driver set to an existing emu world: one
    new ACCL per rank SHARING that rank's device, talking over its own
    same-membership communicator (``key`` disambiguates the comm_id —
    each attached tenant must use a distinct key). This is the
    multi-application shape of the service layer: independent clients,
    one collective engine per rank."""
    ctx = accls[0].device.ctx
    W = ctx.world_size
    out = []
    for r in range(W):
        comm = Communicator(
            ranks=[Rank() for _ in range(W)], local_rank=r, key=key)
        out.append(ACCL(ctx.device(r), comm, timeout=timeout,
                        max_segment_size=max_segment_size, tuner=tuner,
                        tenant=tenant))
    return out


def run_ranks(accls: Sequence[ACCL], fn: Callable[[ACCL], object],
              timeout: float = 60.0) -> list[object]:
    """Run ``fn(accl)`` concurrently on every rank; propagate the first
    exception. This is the SPMD test driver (each thread = one MPI rank of
    the reference's mpirun world)."""
    with concurrent.futures.ThreadPoolExecutor(len(accls)) as pool:
        futs = [pool.submit(fn, a) for a in accls]
        return [f.result(timeout) for f in futs]


def free_port_base(span: int = 64) -> int:
    """Pick a base for a contiguous block of ``span`` ports (cmd + eth
    ranges), verifying every port in the block is currently bindable —
    repeated worlds in one session would otherwise trip over lingering
    listeners or ephemeral client ports from the previous world."""
    import socket
    for _ in range(50):
        probe = socket.create_server(("127.0.0.1", 0))
        base = probe.getsockname()[1] + span
        probe.close()
        if base + span >= 65536:
            continue
        held = []
        try:
            for p in range(base, base + span):
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                # wildcard bind: the daemons bind 0.0.0.0, so the probe
                # must too — a loopback-only probe misses ports held on
                # specific non-loopback interfaces
                s.bind(("", p))
                held.append(s)
        except OSError:
            continue
        finally:
            for s in held:
                s.close()
        if len(held) == span:
            return base
    raise OSError(f"no free block of {span} ports found")


def connect_world(port_base: int, world_size: int,
                  timeout: float = 20.0, host: str = "127.0.0.1",
                  connect_retry_s: float = 10.0) -> list[ACCL]:
    """Connect ACCL drivers to already-running rank daemons (Python or
    native) listening on cmd ports port_base..port_base+W-1. Retries while
    daemons are still starting up."""
    import time

    from .device.sim import SimDevice
    accls = []
    for r in range(world_size):
        comm = Communicator(
            ranks=[Rank(host=host, port=port_base + i, global_rank=i)
                   for i in range(world_size)],
            local_rank=r)
        deadline = time.monotonic() + connect_retry_s
        while True:
            try:
                dev = SimDevice(host, port_base + r)
                break
            except (ConnectionError, OSError):
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)
        accls.append(ACCL(dev, comm, timeout=timeout))
    return accls


def sim_world(world_size: int, nbufs: int = 16, bufsize: int = 1 << 20,
              timeout: float = 20.0, stack: str | None = None
              ) -> list[ACCL]:
    """Create ACCL instances driving out-of-process-style rank daemons over
    the socket protocol (daemons run in-process threads here; the same
    protocol drives true multi-process daemons and the native C++ daemon).
    ``stack`` selects the eth fabric (tcp, udp, or shm — the shared-
    memory dataplane; None reads ``$ACCL_TPU_FABRIC``, default tcp)."""
    from .emulator.daemon import spawn_world
    daemons, port_base = spawn_world(world_size, nbufs=nbufs,
                                     bufsize=bufsize, stack=stack)
    try:
        return connect_world(port_base, world_size, timeout=timeout)
    except Exception:
        # daemons must not outlive a failed connect holding their ports
        for d in daemons:
            d.shutdown()
        raise


def rma_put_under_faults(plan, n: int = 1 << 16, data_seed: int = 3,
                         timeout: float = 30.0) -> bool:
    """Shared body for the RMA payload-corruption scenario (the chaos
    sweep's rma cell and tests/test_integrity.py's rendezvous twin, so
    the two cannot drift): 2-rank emu world, symmetric n-float32 window
    registration, arm ``plan`` (a FaultPlan / inject_fault hook), put a
    seeded random vector rank0 -> rank1's window, and report whether the
    landed window is bit-identical to what was sent. Counter/applied
    assertions stay at the call sites (the sweep checks
    integrity_failed_total moved; the test additionally pins
    plan.applied)."""
    import numpy as np

    accls = emu_world(2, timeout=timeout, nbufs=32)
    fabric = accls[0].device.ctx.fabric
    try:
        wins = {}

        def reg(a):
            buf = a.buffer((n,), np.float32)
            wins[a.rank] = (a.register_window(buf), buf)
        run_ranks(accls, reg, timeout=60.0)
        fabric.inject_fault(plan)
        data = np.random.default_rng(data_seed).standard_normal(n) \
            .astype(np.float32)
        src = accls[0].buffer(data=data.copy())
        accls[0].put(src, n, dst=1, window=wins[1][0])
        return bool((wins[1][1].data == data).all())
    finally:
        fabric.clear_fault()
        for a in accls:
            a.deinit()


def hlo_permute_bytes(hlo: str) -> int:
    """Sum wire bytes over every f32 collective-permute in a compiled HLO
    text: elements x 4 bytes x number of source-target pairs (only listed
    pairs transfer). Shared by the binomial-tree traffic tests (1-D tier
    and the 32-device 2D subprocess) so the byte accounting cannot
    desynchronize between copies."""
    import re
    pat = re.compile(r"f32\[([\d,]*)\]\S*\s+collective-permute\(.*?"
                     r"source_target_pairs=(\{.*?\}\})", re.DOTALL)
    total = 0
    for m in pat.finditer(hlo):
        n = 1
        for d in m.group(1).split(","):
            if d:
                n *= int(d)
        total += n * 4 * max(m.group(2).count("{") - 1, 1)
    return total
