"""Test harness helpers: spin up an N-rank emulated world in-process.

Parity: the reference test story launches N emulator processes under mpirun
and drives each from a Python test process (test/host/test_all.py). The
in-process equivalent here gives the same multi-rank semantics with threads,
for fast unit tests; the socket-daemon tier (emulator/daemon.py) covers the
true multi-process story.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
from typing import Callable, Sequence

from .accl import ACCL
from .communicator import Communicator, Rank
from .device.emu import EmuContext


def emu_world(world_size: int, nbufs: int = 16, bufsize: int | None = None,
              timeout: float = 20.0,
              max_segment_size: int | None = None) -> list[ACCL]:
    """Create ``world_size`` ACCL instances sharing an in-process fabric."""
    kw = {"nbufs": nbufs}
    if bufsize is not None:
        kw["bufsize"] = bufsize
    ctx = EmuContext(world_size, **kw)
    accls = []
    for r in range(world_size):
        comm = Communicator(
            ranks=[Rank() for _ in range(world_size)], local_rank=r)
        accls.append(ACCL(ctx.device(r), comm, timeout=timeout,
                          max_segment_size=max_segment_size))
    return accls


def run_ranks(accls: Sequence[ACCL], fn: Callable[[ACCL], object],
              timeout: float = 60.0) -> list[object]:
    """Run ``fn(accl)`` concurrently on every rank; propagate the first
    exception. This is the SPMD test driver (each thread = one MPI rank of
    the reference's mpirun world)."""
    with concurrent.futures.ThreadPoolExecutor(len(accls)) as pool:
        futs = [pool.submit(fn, a) for a in accls]
        return [f.result(timeout) for f in futs]
