"""SimDevice: driver backend that talks to an out-of-process rank daemon.

Parity: the reference's ``SimDevice``/``SimBuffer`` drive the emulator or
RTL simulator over ZMQ with explicit host<->devicemem copies
(driver/pynq/accl.py:33-159). Here the transport is the framed-TCP protocol
(emulator/protocol.py) and the daemon is either the Python RankDaemon or
the native C++ daemon — the driver cannot tell the difference, which is
the property the reference's 3-tier test story depends on.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
from typing import Sequence

from ..buffer import ACCLBuffer
from ..call import CallDescriptor, CallHandle
from ..communicator import Communicator
from ..constants import CCLOp, ErrorCode
from ..emulator import protocol as P
from .base import Device


class SimDevice(Device):
    """Client to one rank daemon's command socket."""

    def __init__(self, host: str, port: int, connect_timeout: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=connect_timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.settimeout(None)
        self._lock = threading.Lock()          # one in-flight request
        self._buffers: list[ACCLBuffer] = []   # for result-address resolve
        self.timeout = 30.0
        self._request(bytes([P.MSG_PING]))
        # daemon geometry (bufsize bounds the max segment size)
        try:
            info = self._request(bytes([P.MSG_GET_INFO]))
            self._daemon_bufsize = struct.unpack("<Q", info[1:9])[0]
        except Exception:  # older daemons without MSG_GET_INFO
            self._daemon_bufsize = None
        # FIFO dispatch worker: waits each call's local dependencies, THEN
        # syncs operands and submits — an operand sync must not run before a
        # dependency that produces the operand has retired
        self._dispatch_q: queue.Queue = queue.Queue()
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            daemon=True)
        self._dispatcher.start()

    # -- request/reply -----------------------------------------------------
    def _request(self, body: bytes) -> bytes:
        with self._lock:
            P.send_frame(self.sock, body)
            return P.recv_frame(self.sock)

    def _request_status(self, body: bytes) -> int:
        reply = self._request(body)
        assert reply[0] == P.MSG_STATUS, reply[0]
        return struct.unpack("<I", reply[1:5])[0]

    def _check(self, body: bytes):
        err = self._request_status(body)
        if err:
            from ..constants import ACCLError
            raise ACCLError(err, "sim config")

    # -- Device interface --------------------------------------------------
    def register_buffer(self, buf: ACCLBuffer):
        self._check(bytes([P.MSG_ALLOC]) +
                    struct.pack("<2Q", buf.address, buf.nbytes))
        self._buffers.append(buf)

    def deregister_buffer(self, buf: ACCLBuffer):
        self._check(bytes([P.MSG_FREE]) + struct.pack("<Q", buf.address))
        if buf in self._buffers:
            self._buffers.remove(buf)

    def sync_to_device(self, buf: ACCLBuffer):
        data = buf.data.reshape(-1).view("uint8").tobytes()
        self._check(bytes([P.MSG_WRITE_MEM]) +
                    struct.pack("<Q", buf.address) + data)

    def sync_from_device(self, buf: ACCLBuffer):
        reply = self._request(bytes([P.MSG_READ_MEM]) +
                              struct.pack("<2Q", buf.address, buf.nbytes))
        assert reply[0] == P.MSG_DATA
        import numpy as np
        flat = buf.data.reshape(-1).view(np.uint8)
        flat[:] = np.frombuffer(reply[1:], np.uint8)

    def configure_communicator(self, comm: Communicator):
        ranks = [(r.global_rank, r.host, r.port) for r in comm.ranks]
        self._check(P.pack_comm(comm.comm_id, comm.local_rank, ranks))

    def set_timeout(self, timeout: float):
        self.timeout = timeout
        self._check(bytes([P.MSG_SET_TIMEOUT]) + struct.pack("<d", timeout))

    def preferred_segment_size(self) -> int:
        from ..constants import DEFAULT_MAX_SEGMENT_SIZE
        if self._daemon_bufsize:
            return min(self._daemon_bufsize, DEFAULT_MAX_SEGMENT_SIZE)
        return DEFAULT_MAX_SEGMENT_SIZE

    def set_max_segment_size(self, nbytes: int):
        self._check(bytes([P.MSG_SET_SEG]) + struct.pack("<Q", nbytes))

    def soft_reset(self):
        self._check(bytes([P.MSG_RESET]))

    def push_stream(self, data):
        import numpy as np
        arr = np.asarray(data).reshape(-1)
        self._check(bytes([P.MSG_STREAM_PUSH, P.dtype_code(arr.dtype)])
                    + arr.tobytes())

    def pop_stream(self, timeout: float = 0.0, count: int | None = None):
        """Poll MSG_STREAM_POP with short budgets: a blocking request
        would monopolize the single-in-flight command socket for the whole
        timeout, stalling call submission (same discipline as the MSG_WAIT
        completion polling). ``count`` elements, or the next entry whole
        when None (wire encodes that as 0)."""
        import time as _time

        import numpy as np
        deadline = _time.monotonic() + timeout
        while True:
            budget = min(0.05, max(0.0, deadline - _time.monotonic()))
            reply = self._request(bytes([P.MSG_STREAM_POP])
                                  + struct.pack("<dQ", budget, count or 0))
            if reply[0] == P.MSG_DATA:
                return np.frombuffer(reply[2:],
                                     P.code_dtype(reply[1])).copy()
            assert reply[0] == P.MSG_STATUS, reply[0]
            err = struct.unpack("<I", reply[1:5])[0]
            if err != P.STATUS_PENDING:
                # a real daemon-side error must surface, not be spun on
                # until a bogus empty-port timeout (the C++ driver's
                # stream_pop decodes the same way)
                from ..constants import ACCLError
                raise ACCLError(err, "stream pop")
            if _time.monotonic() >= deadline:
                raise IndexError("stream-out port empty")

    def dump_rx_buffers(self) -> str:
        reply = self._request(bytes([P.MSG_DUMP_RX]))
        return reply[1:].decode()

    def get_info(self) -> dict:
        """Daemon geometry + runtime-config state — the readable effect of
        ACCL_CONFIG calls (extended MSG_GET_INFO reply; older daemons
        return only the 20-byte geometry prefix)."""
        reply = self._request(bytes([P.MSG_GET_INFO]))
        assert reply[0] == P.MSG_DATA
        base = struct.unpack("<Q3I", reply[1:21])
        info = {"bufsize": base[0], "nbufs": base[1], "world": base[2],
                "rank": base[3]}
        if len(reply) >= 21 + 18:
            seg, tmo_ms, flags, stack, prof = struct.unpack(
                "<QIBBI", reply[21:39])
            info.update(max_segment_size=seg, timeout_ms=tmo_ms,
                        pkt_enabled=bool(flags & 1),
                        profiling=bool(flags & 2),
                        stack="udp" if stack else "tcp",
                        profiled_calls=prof)
        return info

    def deinit(self):
        self._dispatch_q.put(None)
        try:
            self._request(bytes([P.MSG_SHUTDOWN]))
        except (ConnectionError, OSError):
            pass
        self.sock.close()

    # -- calls -------------------------------------------------------------
    def _resolve_buffer(self, addr: int) -> ACCLBuffer | None:
        for b in self._buffers:
            if b.address <= addr < b.address + b.nbytes:
                return b
        return None

    def call_async(self, desc: CallDescriptor,
                   waitfor: Sequence[CallHandle] = (), *,
                   inline_ok: bool = False) -> CallHandle:
        handle = CallHandle(context=desc.scenario.name)
        waitfor = tuple(waitfor)
        # Inline fast path (shared gate on the Device base): a synchronous
        # call with retired deps dispatches AND polls in the caller's
        # thread when nothing is queued or in flight — saving the
        # dispatch-thread and poll-thread handoffs. NOTE the counter here
        # covers a call only through SUBMISSION (the daemon serializes
        # execution FIFO; a queued call's completion poll may still be
        # running when the counter hits 0) — submission order is what the
        # gate must protect. The cmd socket has its own lock.
        if inline_ok and self._inline_begin(waitfor):
            try:
                self._dispatch_one(desc, waitfor, handle, inline=True)
            finally:
                self._inflight_done()
            return handle
        self._inflight_add()
        self._dispatch_q.put((desc, waitfor, handle))
        return handle

    def _dispatch_loop(self):
        while True:
            item = self._dispatch_q.get()
            if item is None:
                return
            desc, waitfor, handle = item
            try:
                self._dispatch_one(desc, waitfor, handle, inline=False)
            finally:
                self._inflight_done()

    def _dispatch_one(self, desc: CallDescriptor, waitfor,
                      handle: CallHandle, inline: bool):
        """Dep wait + operand sync + submit + completion; never raises."""
        try:
            # local dependency order: operand syncs must observe the
            # dependencies' results (reference collectives sync operands
            # right before starting the call, accl.py:952)
            from ..constants import ACCLError
            try:
                for dep in waitfor:
                    dep.wait(self.timeout)
            except ACCLError as exc:
                handle.complete(exc.error_word, exception=exc)
                return
            for addr in (desc.addr_0, desc.addr_1):
                if addr:
                    b = self._resolve_buffer(addr)
                    if b is not None:
                        self.sync_to_device(b)
            call_id = self._submit(desc)
            handle.sim_call_id = call_id
            if inline:  # the caller is about to block on the handle anyway
                self._poll_completion(desc, call_id, handle)
            else:
                threading.Thread(target=self._poll_completion,
                                 args=(desc, call_id, handle),
                                 daemon=True).start()
        except Exception as exc:  # noqa: BLE001
            handle.complete(int(ErrorCode.CONNECTION_CLOSED),
                            exception=exc)

    def _submit(self, desc: CallDescriptor) -> int:
        cfg = desc.arithcfg
        if cfg is not None:
            ud, cd = P.dtype_code(cfg.uncompressed_dtype), \
                P.dtype_code(cfg.compressed_dtype)
        else:
            ud = cd = P.DTYPE_CODES["float32"]
        body = P.pack_call(int(desc.scenario), int(desc.function),
                           int(desc.compression), int(desc.stream_flags),
                           ud, cd, desc.count, desc.comm_id,
                           desc.root_src_dst,
                           desc.tag & 0xFFFFFFFF,
                           desc.addr_0 or 0, desc.addr_1 or 0,
                           desc.addr_2 or 0, [],
                           algorithm=int(desc.algorithm))
        reply = self._request(body)
        assert reply[0] == P.MSG_CALL_ID
        return struct.unpack("<I", reply[1:5])[0]

    def _poll_completion(self, desc: CallDescriptor, call_id: int,
                         handle: CallHandle):
        """Poll MSG_WAIT with short budgets so the shared command socket is
        never monopolized by one outstanding call (a blocking WAIT would
        serialize — and deadlock symmetric recv-then-send programs)."""
        try:
            while True:
                err = self._request_status(
                    bytes([P.MSG_WAIT]) +
                    struct.pack("<Id", call_id, 0.05))
                if err != P.STATUS_PENDING:
                    break
            if not err:
                res_addr = desc.addr_2 or (
                    desc.addr_0 if desc.scenario == CCLOp.bcast else 0)
                if res_addr:
                    b = self._resolve_buffer(res_addr)
                    if b is not None:
                        self.sync_from_device(b)
            handle.complete(err)
        except Exception as exc:  # noqa: BLE001
            handle.complete(int(ErrorCode.CONNECTION_CLOSED), exception=exc)
