"""SimDevice: driver backend that talks to an out-of-process rank daemon.

Parity: the reference's ``SimDevice``/``SimBuffer`` drive the emulator or
RTL simulator over ZMQ with explicit host<->devicemem copies
(driver/pynq/accl.py:33-159). Here the transport is the framed-TCP protocol
(emulator/protocol.py) and the daemon is either the Python RankDaemon or
the native C++ daemon — the driver cannot tell the difference, which is
the property the reference's 3-tier test story depends on.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
from typing import Sequence

import numpy as np

from ..buffer import ACCLBuffer
from ..call import CallDescriptor, CallHandle
from ..communicator import Communicator
from ..constants import CCLOp, ErrorCode
from ..emulator import protocol as P
from .base import Device


class SimDevice(Device):
    """Client to one rank daemon's command socket."""

    # speculative result-readback bound for async completions: a WAIT
    # that may come back PENDING re-sends its READ on the next poll, so
    # only results cheap enough to re-read ride the fused path
    _SPEC_READ_MAX = 1 << 16

    def __init__(self, host: str, port: int, connect_timeout: float = 10.0):
        self._addr = (host, port)
        self.sock = socket.create_connection((host, port),
                                             timeout=connect_timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.settimeout(None)
        # buffered reader for replies: half the recv syscalls per frame,
        # and batched submissions read many replies per syscall. ALL
        # reads on this socket must go through it from here on.
        self._rfile = self.sock.makefile("rb")
        self._lock = threading.Lock()          # one in-flight request
        self._buffers: list[ACCLBuffer] = []   # for result-address resolve
        self.timeout = 30.0
        self._request(bytes([P.MSG_PING]))
        # daemon geometry (bufsize bounds the max segment size)
        try:
            info = self._request(bytes([P.MSG_GET_INFO]))
            self._daemon_bufsize = struct.unpack("<Q", info[1:9])[0]
        except Exception:  # older daemons without MSG_GET_INFO
            self._daemon_bufsize = None
        # FIFO dispatch worker: waits each call's local dependencies, THEN
        # syncs operands and submits — an operand sync must not run before a
        # dependency that produces the operand has retired
        self._dispatch_q: queue.Queue = queue.Queue()
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            daemon=True)
        self._dispatcher.start()
        # Async completions ride a SECOND daemon connection consumed by
        # one FIFO worker: MSG_WAIT holds its socket until the call
        # retires, and on the (single-in-flight) command socket that
        # would stall every later submission — serializing exactly the
        # chains the wire-waitfor pipelining exists for. Lazy: sync-only
        # clients never open it.
        self._wait_sock: socket.socket | None = None
        self._wait_lock = threading.Lock()
        self._completion_q: queue.Queue | None = None

    # -- request/reply -----------------------------------------------------
    def _request(self, body: bytes) -> bytes:
        with self._lock:
            P.send_frame(self.sock, body)
            return P.recv_frame_file(self._rfile)

    def _ensure_wait_sock(self):
        if self._wait_sock is None:
            self._wait_sock = socket.create_connection(self._addr,
                                                       timeout=10.0)
            self._wait_sock.setsockopt(socket.IPPROTO_TCP,
                                       socket.TCP_NODELAY, 1)
            self._wait_sock.settimeout(None)
            # buffered reader: pipelined replies coalesce in one TCP
            # segment; this turns K replies into ~one syscall
            self._wait_rfile = self._wait_sock.makefile("rb")

    def _request_wait_sock(self, body: bytes) -> bytes:
        """Request on the dedicated completion connection."""
        with self._wait_lock:
            self._ensure_wait_sock()
            P.send_frame(self._wait_sock, body)
            return P.recv_frame_file(self._wait_rfile)

    def _request_many_wait_sock(self, bodies: list[bytes]) -> list[bytes]:
        """Pipelined request batch on the completion connection: one
        coalesced write, replies read in order (the daemon serves a
        connection's frames sequentially)."""
        with self._wait_lock:
            self._ensure_wait_sock()
            P.send_frames(self._wait_sock, bodies)
            return [P.recv_frame_file(self._wait_rfile) for _ in bodies]

    @staticmethod
    def _status_detail(reply: bytes) -> str:
        """Feature name a caps-aware daemon appends (utf-8, after the
        error word) to a failed MSG_STATUS reply — names WHICH capability
        a typed reject is about (e.g. ``alltoallv``, ``block-scaled wire
        dtype``). Legacy daemons reply with exactly 5 bytes -> ``""``."""
        return reply[5:].decode("utf-8", "replace") if len(reply) > 5 else ""

    def _request_status_ex(self, body: bytes) -> "tuple[int, str]":
        reply = self._request(body)
        assert reply[0] == P.MSG_STATUS, reply[0]
        return (struct.unpack("<I", reply[1:5])[0],
                self._status_detail(reply))

    def _request_status(self, body: bytes) -> int:
        return self._request_status_ex(body)[0]

    def _check(self, body: bytes):
        err, detail = self._request_status_ex(body)
        if err:
            from ..constants import ACCLError
            raise ACCLError(err, "sim config"
                            + (f" ({detail})" if detail else ""))

    @staticmethod
    def _tag_feature(handle: CallHandle, detail: str):
        """Fold the daemon's feature name into the handle's context so
        the eventual ``ACCLError`` (raised in ``CallHandle.wait``) says
        *which* feature the daemon rejected, not just the error word."""
        if detail:
            handle.context = ((handle.context + " " if handle.context
                               else "") + f"(daemon rejected: {detail})")

    # -- Device interface --------------------------------------------------
    def register_buffer(self, buf: ACCLBuffer):
        self._check(bytes([P.MSG_ALLOC]) +
                    struct.pack("<2Q", buf.address, buf.nbytes))
        self._buffers.append(buf)

    def deregister_buffer(self, buf: ACCLBuffer):
        self._check(bytes([P.MSG_FREE]) + struct.pack("<Q", buf.address))
        if buf in self._buffers:
            self._buffers.remove(buf)

    def sync_to_device(self, buf: ACCLBuffer):
        data = buf.data.reshape(-1).view("uint8").tobytes()
        self._check(bytes([P.MSG_WRITE_MEM]) +
                    struct.pack("<Q", buf.address) + data)

    @staticmethod
    def _land_result(buf: ACCLBuffer, reply: bytes):
        """Land a MSG_DATA reply into a host-mirror buffer — the ONE copy
        of the landing logic (sync path, inline-fused readback, and the
        completion worker's speculative readback all route here)."""
        assert reply[0] == P.MSG_DATA
        flat = buf.data.reshape(-1).view(np.uint8)
        flat[:] = np.frombuffer(reply, np.uint8, offset=1)

    def sync_from_device(self, buf: ACCLBuffer, request=None):
        """Pull devicemem into the host mirror, optionally over a
        specific connection (the completion worker passes its own)."""
        reply = (request or self._request)(
            bytes([P.MSG_READ_MEM]) +
            struct.pack("<2Q", buf.address, buf.nbytes))
        self._land_result(buf, reply)

    def configure_communicator(self, comm: Communicator,
                               tenant: str | None = None):
        ranks = [(r.global_rank, r.host, r.port) for r in comm.ranks]
        self._check(P.pack_comm(comm.comm_id, comm.local_rank, ranks,
                                tenant=tenant or ""))

    def set_timeout(self, timeout: float):
        self.timeout = timeout
        self._check(bytes([P.MSG_SET_TIMEOUT]) + struct.pack("<d", timeout))

    def preferred_segment_size(self) -> int:
        from ..constants import DEFAULT_MAX_SEGMENT_SIZE
        if self._daemon_bufsize:
            return min(self._daemon_bufsize, DEFAULT_MAX_SEGMENT_SIZE)
        return DEFAULT_MAX_SEGMENT_SIZE

    def topology(self):
        """Socket-daemon tier: a hop pays an RPC to the daemon plus the
        eth-fabric socket transfer (low hundreds of microseconds);
        bandwidth is loopback-TCP-framed. World size from the daemon's
        geometry when it reports one. ``supported`` is the legacy
        ring/rr set: the peer behind the socket may be the native C++
        daemon, which validates and expands only that family — AUTO must
        never resolve to a log-depth algorithm it would reject (explicit
        selectors still pass through to the Python daemon, which
        implements the full family)."""
        from ..tuner.cost import LEGACY_ALGORITHM_PAIRS, Topology
        world = 0
        try:
            world = int(self.get_info().get("world", 0))
        except Exception:  # pre-GET_INFO daemons: world stays unknown
            pass
        return Topology(world_size=world, alpha_us=150.0, beta_gbps=0.5,
                        tier="sim", supported=LEGACY_ALGORITHM_PAIRS)

    def set_max_segment_size(self, nbytes: int):
        self._check(bytes([P.MSG_SET_SEG]) + struct.pack("<Q", nbytes))

    def soft_reset(self):
        self._check(bytes([P.MSG_RESET]))

    def join_handshake(self, comm: Communicator, timeout: float) -> int:
        """Drive the daemon's elastic-membership join handshake
        (MSG_JOIN) with short poll budgets — a long blocking request
        would monopolize the command socket (MSG_STREAM_POP discipline).
        The daemon answers 0 (complete), STATUS_PENDING (peers still
        missing — re-poll until OUR deadline types the failure), or a
        typed error word. A native daemon predating MSG_JOIN answers
        INVALID_CALL, which surfaces as-is: grown communicators are a
        python-daemon/emulator feature until cclo_emud learns the
        message."""
        import time
        sig = comm.membership_signature()
        deadline = time.monotonic() + max(0.05, timeout)
        while True:
            budget = min(0.2, max(0.01, deadline - time.monotonic()))
            reply = self._request(P.pack_join(comm.comm_id, sig, budget))
            assert reply[0] == P.MSG_STATUS, reply[0]
            (err,) = struct.unpack("<I", reply[1:5])
            if err != P.STATUS_PENDING:
                return int(err)
            if time.monotonic() >= deadline:
                return int(ErrorCode.JOIN_FAILED
                           | ErrorCode.RECEIVE_TIMEOUT_ERROR)

    def push_stream(self, data):
        arr = np.asarray(data).reshape(-1)
        self._check(bytes([P.MSG_STREAM_PUSH, P.dtype_code(arr.dtype)])
                    + arr.tobytes())

    def pop_stream(self, timeout: float = 0.0, count: int | None = None):
        """Poll MSG_STREAM_POP with short budgets: a blocking request
        would monopolize the single-in-flight command socket for the whole
        timeout, stalling call submission (same discipline as the MSG_WAIT
        completion polling). ``count`` elements, or the next entry whole
        when None (wire encodes that as 0)."""
        import time as _time
        deadline = _time.monotonic() + timeout
        while True:
            budget = min(0.05, max(0.0, deadline - _time.monotonic()))
            reply = self._request(bytes([P.MSG_STREAM_POP])
                                  + struct.pack("<dQ", budget, count or 0))
            if reply[0] == P.MSG_DATA:
                return np.frombuffer(reply[2:],
                                     P.code_dtype(reply[1])).copy()
            assert reply[0] == P.MSG_STATUS, reply[0]
            err = struct.unpack("<I", reply[1:5])[0]
            if err != P.STATUS_PENDING:
                # a real daemon-side error must surface, not be spun on
                # until a bogus empty-port timeout (the C++ driver's
                # stream_pop decodes the same way)
                from ..constants import ACCLError
                raise ACCLError(err, "stream pop")
            if _time.monotonic() >= deadline:
                raise IndexError("stream-out port empty")

    def dump_rx_buffers(self) -> str:
        reply = self._request(bytes([P.MSG_DUMP_RX]))
        return reply[1:].decode()

    def rx_capacity(self) -> tuple[int, int]:
        """(nbufs, bufsize) of the daemon's rx pool — the preflight
        surface (ACCL.preflight / hierarchical rx-pool sizing check)."""
        info = self.get_info()
        return (int(info["nbufs"]), int(info["bufsize"]))

    # -- one-sided RMA windows (accl_tpu/rma) ------------------------------
    def register_window(self, wid: int, addr: int, nbytes: int):
        """Register a window on the daemon (MSG_REG_WINDOW). The backing
        buffer's host mirror is pushed first: a peer's get against a
        freshly registered window must see the buffer's current
        contents, and remote puts land daemon-side only (sync the buffer
        from the device to observe them, as with collective results)."""
        buf = self._resolve_buffer(addr)
        if buf is not None:
            self.sync_to_device(buf)
        self._check(bytes([P.MSG_REG_WINDOW])
                    + struct.pack("<IQQ", wid, addr, nbytes))

    def deregister_window(self, wid: int):
        self._check(bytes([P.MSG_REG_WINDOW])
                    + struct.pack("<IQQ", wid, 0, 0))

    def poll_notifications(self, window: int, max_records: int = 64):
        """Drain put-with-notify completions from the daemon
        (MSG_RMA_NOTIFY): one cmd-port round trip to THIS rank's daemon,
        nothing on the data fabric. Native daemons without the notify
        lane answer INVALID_CALL — surfaced typed, never spun on."""
        from ..rma.notify import NotifyRecord
        reply = self._request(P.pack_notify_poll(window, max_records))
        if reply[0] == P.MSG_STATUS:
            err = struct.unpack("<I", reply[1:5])[0]
            from ..constants import ACCLError
            raise ACCLError(err, "notify poll")
        assert reply[0] == P.MSG_DATA, reply[0]
        return [NotifyRecord(*rec)
                for rec in P.unpack_notify_records(reply[1:])]

    def get_info(self) -> dict:
        """Daemon geometry + runtime-config state — the readable effect of
        ACCL_CONFIG calls (extended MSG_GET_INFO reply; older daemons
        return only the 20-byte geometry prefix)."""
        reply = self._request(bytes([P.MSG_GET_INFO]))
        assert reply[0] == P.MSG_DATA
        base = struct.unpack("<Q3I", reply[1:21])
        info = {"bufsize": base[0], "nbufs": base[1], "world": base[2],
                "rank": base[3]}
        if len(reply) >= 21 + 18:
            seg, tmo_ms, flags, stack, prof = struct.unpack(
                "<QIBBI", reply[21:39])
            info.update(max_segment_size=seg, timeout_ms=tmo_ms,
                        pkt_enabled=bool(flags & 1),
                        profiling=bool(flags & 2),
                        stack="udp" if stack else "tcp",
                        profiled_calls=prof)
        if len(reply) >= 21 + 22:
            # capability word (absent on native/older daemons -> 0):
            # bit 0 retx-ACK responder, bit 1 one-sided RMA
            info["caps"] = struct.unpack("<I", reply[39:43])[0]
        else:
            info["caps"] = 0
        return info

    def deinit(self):
        # the dispatcher forwards the completion sentinel AFTER draining
        # its queue — a sentinel enqueued here directly would overtake
        # completions of still-undispatched calls and strand their
        # handles forever
        self._dispatch_q.put(None)
        try:
            self._request(bytes([P.MSG_SHUTDOWN]))
        except (ConnectionError, OSError):
            pass
        self.sock.close()
        if self._wait_sock is not None:
            self._wait_sock.close()

    # -- calls -------------------------------------------------------------
    @staticmethod
    def _result_addr(desc: CallDescriptor) -> int:
        """The address a completed call wrote (bcast lands in-place). A
        put writes nothing locally — and its addr_2 carries the notify
        token, which must never be resolved as a result address."""
        if desc.scenario == CCLOp.put:
            return 0
        return desc.addr_2 or (
            desc.addr_0 if desc.scenario == CCLOp.bcast else 0)

    @staticmethod
    def _operand_addrs(desc: CallDescriptor) -> tuple:
        """Operand addresses whose host mirrors must be pushed before
        submission. One-sided calls carry the WINDOW OFFSET in addr_1 —
        a small integer that could alias an unrelated buffer's address
        range, so it must never be resolved as an operand."""
        if desc.scenario in (CCLOp.put, CCLOp.get):
            return (desc.addr_0,)
        return (desc.addr_0, desc.addr_1)

    def _resolve_buffer(self, addr: int) -> ACCLBuffer | None:
        for b in self._buffers:
            if b.address <= addr < b.address + b.nbytes:
                return b
        return None

    def call_async(self, desc: CallDescriptor,
                   waitfor: Sequence[CallHandle] = (), *,
                   inline_ok: bool = False) -> CallHandle:
        handle = CallHandle(context=desc.scenario.name)
        waitfor = tuple(waitfor)
        # Inline fast path (shared gate on the Device base): a synchronous
        # call with retired deps dispatches AND polls in the caller's
        # thread when nothing is queued or in flight — saving the
        # dispatch-thread and poll-thread handoffs. NOTE the counter here
        # covers a call only through SUBMISSION (the daemon serializes
        # execution FIFO; a queued call's completion poll may still be
        # running when the counter hits 0) — submission order is what the
        # gate must protect. The cmd socket has its own lock.
        if inline_ok and self._inline_begin(waitfor):
            try:
                self._dispatch_one(desc, waitfor, handle, inline=True)
            finally:
                self._inflight_done()
            return handle
        self._inflight_add()
        self._dispatch_q.put((desc, waitfor, handle))
        return handle

    def _dispatch_loop(self):
        while True:
            item = self._dispatch_q.get()
            if item is None:
                if self._completion_q is not None:
                    self._completion_q.put(None)
                return
            # Drain whatever else is already queued: consecutive
            # pipeline-eligible items submit as ONE coalesced write
            # (chain links otherwise pay a full request round-trip
            # each — the serialization the wire-waitfor design removes).
            # Once the batch contains a chained item (non-empty waitfor)
            # the submitter is mid-chain, so a sub-millisecond grace get
            # captures the links it is still enqueueing; independent
            # single calls never wait.
            batch = [item]
            chaining = bool(item[1])
            while len(batch) < 64:
                try:
                    nxt = (self._dispatch_q.get(timeout=0.0005)
                           if chaining else self._dispatch_q.get_nowait())
                except queue.Empty:
                    break
                if nxt is None:
                    self._dispatch_q.put(None)  # re-deliver shutdown
                    break
                batch.append(nxt)
                chaining = chaining or bool(nxt[1])
            try:
                self._dispatch_batch(batch)
            finally:
                for _ in batch:
                    self._inflight_done()

    def _dispatch_batch(self, batch: list):
        """Submit a drained run of calls, grouping pipeline-eligible
        stretches into single coalesced writes; non-eligible items fall
        back to the one-at-a-time path."""
        run: list = []
        for item in batch:
            desc, waitfor, handle = item
            if self._pipeline_eligible(desc, waitfor, run):
                handle.sim_hazard_addrs = self._hazard_footprint(desc,
                                                                 waitfor)
                run.append(item)
                continue
            self._flush_run(run)
            run = []
            self._dispatch_one(desc, waitfor, handle, inline=False)
        self._flush_run(run)

    def _hazard_footprint(self, desc: CallDescriptor, waitfor) -> tuple:
        """Addresses an unretired chain rooted at this call may still
        READ or WRITE: its own operands + result, plus every pending
        dependency's footprint (transitively, via the footprints stored
        on their handles at submission). Conservative — retired calls
        leave stale entries that only cause a harmless fallback."""
        fp = {a for a in (*self._operand_addrs(desc),
                          self._result_addr(desc)) if a}
        for dep in waitfor:
            if not dep.done():
                fp.update(getattr(dep, "sim_hazard_addrs", ()))
        return tuple(fp)

    def _pipeline_eligible(self, desc: CallDescriptor, waitfor,
                           run: list) -> bool:
        """True iff every dependency is an already-submitted call on this
        daemon (or the immediately preceding item of the current run) AND
        submitting now is operand-safe."""
        prev = run[-1] if run else None
        for dep in waitfor:
            if prev is not None and dep is prev[2]:
                # footprint of the preceding in-run item (computed and
                # stashed on its handle when it was appended)
                dep_fp = getattr(prev[2], "sim_hazard_addrs", ())
                dep_res = self._result_addr(prev[0])
                dep_done = False
            elif (getattr(dep, "sim_device", None) is self
                    and getattr(dep, "sim_call_id", None) is not None):
                dep_fp = getattr(dep, "sim_hazard_addrs", ())
                dep_res = getattr(dep, "sim_result_addr", 0)
                dep_done = dep.done()
            else:
                return False
            if dep_done:
                continue  # retired: our operand push can't clobber it
            # Operand hazard: pipelined submission pushes THIS call's
            # operand mirrors before the dependency chain executes. If
            # an operand aliases ANY buffer the unretired chain still
            # reads or writes (the dependency's transitive footprint) —
            # other than the direct dependency's result, which we never
            # push — the push would feed the chain data from the
            # future; fall back to the wait-then-sync path.
            res_buf = self._resolve_buffer(dep_res) if dep_res else None
            for addr in self._operand_addrs(desc):
                if not addr:
                    continue
                b = self._resolve_buffer(addr)
                if b is None or b is res_buf:
                    continue
                for da in dep_fp:
                    if da and self._resolve_buffer(da) is b:
                        return False
        return True

    def _flush_run(self, run: list):
        """One coalesced submission for a pipeline-eligible run."""
        if not run:
            return
        if len(run) == 1:
            desc, waitfor, handle = run[0]
            self._dispatch_one(desc, waitfor, handle, inline=False)
            return
        try:
            bodies = []
            for i, (desc, waitfor, handle) in enumerate(run):
                prev_handle = run[i - 1][2] if i else None
                wire_waitfor = []
                skip_bufs = []
                for dep in waitfor:
                    if dep is prev_handle:
                        wire_waitfor.append(P.WAITFOR_PREV)
                        ra = self._result_addr(run[i - 1][0])
                    else:
                        wire_waitfor.append(dep.sim_call_id)
                        # pending deps only: a retired dependency's
                        # result mirror is authoritative again
                        ra = (0 if dep.done()
                              else getattr(dep, "sim_result_addr", 0))
                    if ra:
                        skip_bufs.append(self._resolve_buffer(ra))
                # operand pushes go BEFORE the batched submissions (the
                # daemon handles WRITE_MEM on arrival, before any of the
                # batch executes); dependency-produced operands live in
                # devicemem and must NOT be clobbered by stale mirrors
                for addr in self._operand_addrs(desc):
                    if addr:
                        b = self._resolve_buffer(addr)
                        if b is not None and b not in skip_bufs:
                            self.sync_to_device(b)
                bodies.append(self._call_body(desc, wire_waitfor))
            with self._lock:
                P.send_frames(self.sock, bodies)
                ids = []
                for _ in bodies:
                    reply = P.recv_frame_file(self._rfile)
                    assert reply[0] == P.MSG_CALL_ID
                    ids.append(struct.unpack("<I", reply[1:5])[0])
            if self._completion_q is None:
                self._completion_q = queue.Queue()
                threading.Thread(target=self._completion_loop,
                                 daemon=True).start()
            for (desc, _wf, handle), call_id in zip(run, ids):
                handle.sim_call_id = call_id
                handle.sim_device = self
                handle.sim_result_addr = self._result_addr(desc)
                handle.sim_operand_addrs = self._operand_addrs(desc)
                self._completion_q.put((desc, call_id, handle))
        except Exception as exc:  # noqa: BLE001
            for _desc, _wf, handle in run:
                if not handle.done():
                    handle.complete(int(ErrorCode.CONNECTION_CLOSED),
                                    exception=exc)

    def _dispatch_one(self, desc: CallDescriptor, waitfor,
                      handle: CallHandle, inline: bool):
        """Dep wait + operand sync + submit + completion; never raises."""
        try:
            from ..constants import ACCLError
            # Pipelined chain submission (hostctrl ap_ctrl_chain parity:
            # the reference chains async calls in hardware without host
            # round-trips between links, hostctrl.cpp:56-90). When every
            # dependency is an already-submitted call on THIS daemon, the
            # chain's ordering and error propagation live daemon-side
            # (FIFO worker + wire waitfor ids), so this link submits
            # immediately instead of blocking on the dep's host-visible
            # completion — an N-deep chain costs N pipelined submissions,
            # not N serialized round-trip latencies.
            wire_waitfor: list[int] = []
            dep_result_bufs: list = []
            pipelined = bool(waitfor) and self._pipeline_eligible(
                desc, waitfor, [])
            if pipelined:
                for dep in waitfor:
                    wire_waitfor.append(dep.sim_call_id)
                    ra = getattr(dep, "sim_result_addr", 0)
                    # skip-push only applies to a PENDING dependency's
                    # result (its value exists solely in devicemem); a
                    # retired dependency's result was synced back, and a
                    # host mutation made after that must be honored
                    if ra and not dep.done():
                        dep_result_bufs.append(self._resolve_buffer(ra))
            if not pipelined:
                # local dependency order: operand syncs must observe the
                # dependencies' results (reference collectives sync
                # operands right before starting the call, accl.py:952)
                wire_waitfor = []
                dep_result_bufs = []
                try:
                    for dep in waitfor:
                        dep.wait(self.timeout)
                except ACCLError as exc:
                    handle.complete(exc.error_word, exception=exc)
                    return
            sync_bufs = []
            for addr in self._operand_addrs(desc):
                if addr:
                    b = self._resolve_buffer(addr)
                    # a pipelined dependency PRODUCES this operand in
                    # devicemem; pushing the stale host mirror would race
                    # the dependency's execution and clobber its result
                    if b is not None and b not in dep_result_bufs:
                        sync_bufs.append(b)
            if inline:
                # Fully fused synchronous call: operand pushes + submit +
                # first wait + speculative result readback go out as ONE
                # pipelined write and the replies stream back — 1 client
                # round trip instead of 3-4 serialized ones (the Python
                # daemon's latency floor was dominated by exactly these).
                self._inline_fused(desc, wire_waitfor, sync_bufs, handle,
                                   waitfor)
                return
            for b in sync_bufs:
                self.sync_to_device(b)
            call_id = self._submit(desc, wire_waitfor)
            handle.sim_call_id = call_id
            handle.sim_device = self
            handle.sim_result_addr = self._result_addr(desc)
            handle.sim_operand_addrs = self._operand_addrs(desc)
            handle.sim_hazard_addrs = self._hazard_footprint(desc, waitfor)
            # single FIFO completion worker on the dedicated wait
            # connection (daemon retirement is FIFO, so head-of-queue
            # waiting is optimal — and per-call poller threads used
            # to contend with submissions on the command socket)
            if self._completion_q is None:
                self._completion_q = queue.Queue()
                threading.Thread(target=self._completion_loop,
                                 daemon=True).start()
            self._completion_q.put((desc, call_id, handle))
        except Exception as exc:  # noqa: BLE001
            handle.complete(int(ErrorCode.CONNECTION_CLOSED),
                            exception=exc)

    def _call_body(self, desc: CallDescriptor,
                   waitfor_ids: Sequence[int]) -> bytes:
        cfg = desc.arithcfg
        if cfg is not None:
            ud, cd = P.dtype_code(cfg.uncompressed_dtype), \
                P.dtype_code(cfg.compressed_dtype)
        else:
            ud = cd = P.DTYPE_CODES["float32"]
        return P.pack_call(int(desc.scenario), int(desc.function),
                           int(desc.compression), int(desc.stream_flags),
                           ud, cd, desc.count, desc.comm_id,
                           desc.root_src_dst,
                           desc.tag & 0xFFFFFFFF,
                           desc.addr_0 or 0, desc.addr_1 or 0,
                           desc.addr_2 or 0, list(waitfor_ids),
                           algorithm=int(desc.algorithm),
                           qblock=(cfg.quant_block
                                   if cfg is not None else 0),
                           counts=desc.counts)

    def _submit(self, desc: CallDescriptor,
                waitfor_ids: Sequence[int] = ()) -> int:
        reply = self._request(self._call_body(desc, waitfor_ids))
        assert reply[0] == P.MSG_CALL_ID
        return struct.unpack("<I", reply[1:5])[0]

    def _inline_fused(self, desc: CallDescriptor, wire_waitfor,
                      sync_bufs, handle: CallHandle, waitfor):
        """One-round-trip synchronous call: pipeline [operand pushes,
        MSG_CALL, MSG_WAIT(budget), MSG_READ_MEM(result)] in a single
        write; the daemon's connection thread executes them in order
        (the WAIT blocks it until the call retires) and streams the
        replies. A PENDING first wait falls back to the budget-polling
        loop; the speculative readback is discarded on error or PENDING
        (stale bytes, never used)."""
        res_addr = self._result_addr(desc)
        res_buf = self._resolve_buffer(res_addr) if res_addr else None
        frames = [bytes([P.MSG_WRITE_MEM]) + struct.pack("<Q", b.address)
                  + b.data.reshape(-1).view("uint8").tobytes()
                  for b in sync_bufs]
        frames.append(self._call_body(desc, wire_waitfor))
        # WAIT_LAST sentinel: the wait names "the call this connection
        # just submitted", so the entire sequence ships in ONE write and
        # the client blocks exactly once, reading the reply stream
        frames.append(bytes([P.MSG_WAIT]) +
                      struct.pack("<Id", P.WAIT_LAST, 0.25))
        if res_buf is not None:
            frames.append(bytes([P.MSG_READ_MEM]) + struct.pack(
                "<2Q", res_buf.address, res_buf.nbytes))
        sync_err = 0
        with self._lock:
            P.send_frames(self.sock, frames)
            for _ in sync_bufs:
                reply = P.recv_frame_file(self._rfile)
                assert reply[0] == P.MSG_STATUS
                sync_err |= struct.unpack("<I", reply[1:5])[0]
            reply = P.recv_frame_file(self._rfile)
            assert reply[0] == P.MSG_CALL_ID
            call_id = struct.unpack("<I", reply[1:5])[0]
            wait_reply = P.recv_frame_file(self._rfile)
            data_reply = (P.recv_frame_file(self._rfile)
                          if res_buf is not None else None)
        handle.sim_call_id = call_id
        handle.sim_device = self
        handle.sim_result_addr = res_addr
        handle.sim_operand_addrs = self._operand_addrs(desc)
        handle.sim_hazard_addrs = self._hazard_footprint(desc, waitfor)
        if sync_err:
            # an operand push failed after the call was already
            # pipelined; surface the push error (the call's own result
            # is meaningless on stale operands)
            handle.complete(sync_err)
            return
        assert wait_reply[0] == P.MSG_STATUS
        err = struct.unpack("<I", wait_reply[1:5])[0]
        if err == P.STATUS_PENDING:
            # slow call (blocking recv, big collective): budget-poll as
            # before; the speculative readback is repeated post-success
            self._poll_completion(desc, call_id, handle)
            return
        if not err and data_reply is not None:
            self._land_result(res_buf, data_reply)
        if err:
            self._tag_feature(handle, self._status_detail(wait_reply))
        handle.complete(err)

    def _poll_completion(self, desc: CallDescriptor, call_id: int,
                         handle: CallHandle):
        """Inline (synchronous-call) completion on the shared command
        socket: short MSG_WAIT budgets so it is never monopolized by one
        outstanding call (a blocking WAIT would serialize — and deadlock
        symmetric recv-then-send programs)."""
        try:
            while True:
                err, detail = self._request_status_ex(
                    bytes([P.MSG_WAIT]) +
                    struct.pack("<Id", call_id, 0.05))
                if err != P.STATUS_PENDING:
                    break
            if err:
                self._tag_feature(handle, detail)
            self._finish_call(desc, err, handle, self._request)
        except Exception as exc:  # noqa: BLE001
            handle.complete(int(ErrorCode.CONNECTION_CLOSED), exception=exc)

    def _completion_loop(self):
        """FIFO completion worker on the dedicated wait connection.
        Drains its queue and pipelines a batch of MSG_WAITs in one write:
        the daemon's connection thread blocks per wait until the call
        retires and streams the replies back in retirement order — the
        client just reads them. Long budgets are fine here (MSG_WAIT
        returns the moment the call retires; nothing else uses this
        socket)."""
        while True:
            item = self._completion_q.get()
            if item is None:
                return
            batch = [item]
            while len(batch) < 64:
                try:
                    nxt = self._completion_q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._completion_q.put(None)
                    break
                batch.append(nxt)
            pending = batch
            first_round = True
            try:
                while pending:
                    # Only the HEAD wait carries a blocking budget: FIFO
                    # retirement means once the head retires the daemon
                    # answers the zero-budget probes for the rest
                    # immediately (a budget per entry would serialize a
                    # full second per still-pending call). Each wait is
                    # followed by a SPECULATIVE result readback in the
                    # same pipelined write (small results only): the
                    # retire->complete path costs one round trip instead
                    # of wait-then-read — the data reply is discarded
                    # when the wait comes back PENDING or failed (stale
                    # bytes, never used; same discipline as
                    # _inline_fused's speculative readback).
                    frames: list[bytes] = []
                    spec_bufs = []
                    for i, (desc, call_id, _h) in enumerate(pending):
                        frames.append(bytes([P.MSG_WAIT]) +
                                      struct.pack("<Id", call_id,
                                                  1.0 if i == 0 else 0.0))
                        # retry rounds (the previous head probe came back
                        # PENDING) speculate only on the head: FIFO
                        # retirement means nothing behind a still-pending
                        # head can have retired either, so per-entry
                        # re-reads would ship data that is discarded by
                        # construction
                        if not first_round and i > 0:
                            spec_bufs.append(None)
                            continue
                        res_addr = self._result_addr(desc)
                        res_buf = (self._resolve_buffer(res_addr)
                                   if res_addr else None)
                        if (res_buf is not None
                                and res_buf.nbytes <= self._SPEC_READ_MAX):
                            frames.append(bytes([P.MSG_READ_MEM]) +
                                          struct.pack("<2Q", res_buf.address,
                                                      res_buf.nbytes))
                            spec_bufs.append(res_buf)
                        else:
                            spec_bufs.append(None)
                    first_round = False
                    replies = self._request_many_wait_sock(frames)
                    it = iter(replies)
                    nxt_pending = []
                    for (desc, call_id, handle), res_buf in zip(pending,
                                                                spec_bufs):
                        reply = next(it)
                        assert reply[0] == P.MSG_STATUS, reply[0]
                        err = struct.unpack("<I", reply[1:5])[0]
                        data_reply = (next(it) if res_buf is not None
                                      else None)
                        if err == P.STATUS_PENDING:
                            nxt_pending.append((desc, call_id, handle))
                            continue
                        if err:
                            self._tag_feature(
                                handle, self._status_detail(reply))
                        if not err and res_buf is not None:
                            self._land_result(res_buf, data_reply)
                            handle.complete(err)
                        else:
                            # big/absent result, or a failed call whose
                            # speculative bytes must not land in the
                            # host mirror
                            self._finish_call(desc, err, handle,
                                              self._request_wait_sock)
                    pending = nxt_pending
            except Exception as exc:  # noqa: BLE001
                for _desc, _cid, handle in pending:
                    if not handle.done():
                        handle.complete(int(ErrorCode.CONNECTION_CLOSED),
                                        exception=exc)

    def _finish_call(self, desc: CallDescriptor, err: int,
                     handle: CallHandle, request):
        """Result readback (over the given connection) + completion."""
        if not err:
            res_addr = self._result_addr(desc)
            if res_addr:
                b = self._resolve_buffer(res_addr)
                if b is not None:
                    self.sync_from_device(b, request=request)
        handle.complete(err)
