"""In-process threaded CPU emulator backend.

N ranks live in one process, each with its own device memory, RX buffer
pool, move executor and a worker thread that retires queued calls in order.
The fabric is the in-process loopback (emulator/fabric.py).

Parity: this plays the role of the reference's single-process loopback
builds (multi-CCLO on one board through dummy_tcp_stack) and is the fast
tier of the 3-tier test story (§4 of SURVEY.md). The out-of-process daemon
(emulator/daemon.py + native/) reuses exactly these engines behind a socket
protocol, mirroring cclo_emu.cpp behind ZMQ.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time
from typing import Sequence

from ..buffer import ACCLBuffer
from ..call import CallDescriptor, CallHandle
from ..communicator import Communicator
from ..constants import (ACCLError, CCLOp, Compression,
                         DEFAULT_CALL_CHAIN_DEPTH,
                         DEFAULT_MAX_SEGMENT_SIZE, DEFAULT_RX_BUFFER_COUNT,
                         DEFAULT_RX_BUFFER_SIZE, DEFAULT_TIMEOUT_S,
                         ErrorCode, StreamFlags)
from ..plancache import PlanCache, cached_program
from ..emulator.executor import DeviceMemory, MoveExecutor, RxBufferPool
from ..emulator.fabric import Envelope, LocalFabric
from ..service import RankService, ServiceConfig, service_enabled, \
    tenant_label
from .base import Device

# inbox token waking the ingress loop's deferred retry (pool release)
_RETRY = object()
_ETH_C = Compression.ETH_COMPRESSED


class EmuContext:
    """Shared state of an N-rank in-process emulation: the fabric.

    ``pipeline_window`` sets each rank's executor in-flight window depth
    (None = the process default, 0 = strict serial reference engine);
    ``segment_stream`` selects the dependency-aware segment pipeline vs
    the send-only window (None = the process default, on); ``plan_cache``
    enables/disables the compiled-plan cache (None = the process default,
    ``$ACCL_TPU_PLAN_CACHE``)."""

    def __init__(self, world_size: int, nbufs: int = DEFAULT_RX_BUFFER_COUNT,
                 bufsize: int = DEFAULT_RX_BUFFER_SIZE,
                 pipeline_window: int | None = None,
                 segment_stream: bool | None = None,
                 plan_cache: bool | None = None,
                 service: "ServiceConfig | bool | None" = None,
                 hosts=None, inter_alpha_us: float | None = None,
                 inter_beta_gbps: float | None = None,
                 outer_tiers=None,
                 retx_window: int | None = None,
                 csum: bool | None = None):
        self.world_size = world_size
        # ``retx_window`` sets the fabric's selective-retransmission
        # in-flight window (None = $ACCL_TPU_RETX_WINDOW / process
        # default, 0 = pre-retransmit fault-surfacing behavior);
        # ``csum`` arms/disarms payload checksums (None = $ACCL_TPU_CSUM,
        # default on — the corrupt-as-loss integrity tier)
        self.fabric = LocalFabric(world_size, retx_window=retx_window,
                                  csum=csum)
        # membership: heartbeat thread state (armed via start_heartbeats)
        self._hb_stop: threading.Event | None = None
        self._hb_killed: set[int] = set()
        self.hb_interval = 0.0
        self.hb_budget = 3
        # two-tier emulation (accl_tpu/hier): ``hosts`` maps rank->host
        # id (contiguous runs). Devices then report a MeshTopology so an
        # attached tuner prices hierarchical phase programs, and — when
        # inter-tier figures are given — the fabric emulates the slow
        # tier on every cross-host link (set_tier_profile), so measured
        # crossovers are real, not just modeled.
        self.hosts = list(hosts) if hosts is not None else None
        # normalize ONCE so the emulated fabric and the reported
        # MeshTopology can never disagree about the slow tier: a
        # partially-specified profile fills the other figure from the
        # same defaults topology() reports
        self.throttle_inter = (inter_alpha_us is not None
                               or inter_beta_gbps is not None)
        self.inter_alpha_us = (200.0 if inter_alpha_us is None
                               else float(inter_alpha_us))
        self.inter_beta_gbps = (0.4 if inter_beta_gbps is None
                                else float(inter_beta_gbps))
        if self.hosts is None:
            if self.throttle_inter:
                # a slow-tier profile with no grouping would be
                # silently ignored — a test believing it emulates DCN
                # would measure the unthrottled loopback with no error
                raise ValueError(
                    "inter_alpha_us/inter_beta_gbps require hosts= "
                    "(the rank->host grouping names the cross-host "
                    "links to throttle)")
        else:
            if len(self.hosts) != world_size:
                raise ValueError(f"hosts maps {len(self.hosts)} ranks, "
                                 f"world is {world_size}")
            # fail at the misconfiguration site, not later from inside a
            # tuner's topology() query: the hierarchy machinery requires
            # contiguous host runs (groups_from_hosts validates)
            from ..hier import groups_from_hosts
            groups_from_hosts(self.hosts)
            if self.throttle_inter and len(set(self.hosts)) < 2:
                # same silent-failure class the hosts=None guard
                # catches: one distinct host has no cross-host link for
                # the profile to throttle
                raise ValueError(
                    "inter_alpha_us/inter_beta_gbps need at least two "
                    "distinct hosts — a one-host grouping has no "
                    "cross-host links to throttle")
            if self.throttle_inter:
                # set_link_profile validates beta > 0
                self.fabric.set_tier_profile(
                    self.hosts, self.inter_alpha_us,
                    self.inter_beta_gbps)
        # N-tier emulation: each ``outer_tiers`` entry is a coarser
        # ``(hosts_map, alpha_us, beta_gbps)`` boundary innermost-first
        # (rack, pod, ...). Profiles apply in->out so a coarser (slower)
        # boundary overwrites the cross-group pairs of the finer one —
        # a cross-rack link ends up with rack figures, a cross-host
        # same-rack link keeps host figures.
        self.outer_tiers = ([(list(h), float(a), float(b))
                             for h, a, b in outer_tiers]
                            if outer_tiers else [])
        if self.outer_tiers:
            if self.hosts is None:
                raise ValueError(
                    "outer_tiers require hosts= (coarser boundaries "
                    "must enclose the host grouping)")
            from ..hier import groups_from_hosts as _gfh
            from ..hier.topology import validate_nest
            for h, _a, _b in self.outer_tiers:
                if len(h) != world_size:
                    raise ValueError(f"outer tier maps {len(h)} ranks, "
                                     f"world is {world_size}")
            validate_nest((_gfh(self.hosts),)
                          + tuple(_gfh(h) for h, _a, _b in self.outer_tiers))
            for h, a, b in self.outer_tiers:
                self.fabric.set_tier_profile(h, a, b)
        # multi-tenant service config shared by every rank of this world
        # (policy only; per-rank controllers/quotas live on the devices).
        # None = process default ($ACCL_TPU_SERVICE, on); False = off;
        # True = default config; a ServiceConfig = explicit policy.
        if service is None:
            service = ServiceConfig() if service_enabled() else None
        elif service is True:
            service = ServiceConfig(enabled=True)
        elif service is False:
            service = None
        if service is not None and not service.enabled:
            service = None
        self.service_config = service
        # unified metrics: the shared fabric reports once per CONTEXT
        # (per-rank collectors would multiply its counters by W); weak
        # registration, so a torn-down world stops reporting
        from ..tracing import METRICS
        METRICS.register_collector(self.fabric, LocalFabric.metrics_rows)
        self.nbufs, self.bufsize = nbufs, bufsize
        self.pipeline_window = pipeline_window
        self.segment_stream = segment_stream
        self.plan_cache = plan_cache
        self.devices: list[EmuDevice | None] = [None] * world_size
        self._deinit_count = 0

    def note_device_deinit(self):
        """Called by each EmuDevice.deinit: once the whole world has
        torn down, an armed heartbeat thread must die with it (it holds
        the context alive through its references and would spin
        forever — worlds are created by the thousands per session)."""
        self._deinit_count += 1
        if self._deinit_count >= self.world_size:
            self.stop_heartbeats()

    def device(self, rank: int) -> "EmuDevice":
        if self.devices[rank] is None:
            dev = EmuDevice(self, rank)
            self.devices[rank] = dev
            self.fabric.attach(rank, dev.ingest)
            # retransmit give-up latches PEER_FAILED into the rank's
            # CURRENT pool (closure — soft reset swaps the pool object)
            self.fabric.set_latch(
                rank, lambda cid, err, d=dev: d.pool.latch_error(cid, err))
        return self.devices[rank]

    # -- membership (heartbeats) -------------------------------------------
    def start_heartbeats(self, interval_s: float = 0.05, budget: int = 3):
        """Arm heartbeat-based peer-failure detection for this world: one
        context thread emits per-rank heartbeat frames through the fabric
        (so a chaos partition or :meth:`kill_rank` silences them exactly
        like data), and each device tracks its peers' last-heard times.
        A peer silent past ``budget`` intervals is declared dead:
        PEER_FAILED latches on every comm containing it, waiting programs
        abort immediately, and new calls on those comms fail fast — other
        communicators keep flowing. Off by default (tests/worlds opt in;
        steady-state cost is W^2 tiny frames per interval)."""
        if self._hb_stop is not None:
            return
        self.hb_interval = float(interval_s)
        self.hb_budget = max(1, int(budget))
        self._hb_stop = threading.Event()
        threading.Thread(target=self._hb_loop, daemon=True,
                         name="emu-heartbeat").start()

    def stop_heartbeats(self):
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_stop = None

    def kill_rank(self, rank: int):
        """Inject a rank death: the rank stops heartbeating (its device
        threads stay up — in-process ranks share fate — but to its peers
        it is indistinguishable from a crashed host). Combine with a
        chaos partition to also silence its data frames."""
        self._hb_killed.add(rank)

    def revive_rank(self, rank: int):
        self._hb_killed.discard(rank)

    def _hb_loop(self):
        from ..emulator.protocol import HB_STRM
        stop = self._hb_stop
        while stop is not None and not stop.wait(self.hb_interval):
            for r, dev in enumerate(self.devices):
                if dev is None or r in self._hb_killed:
                    continue
                for q in range(self.world_size):
                    if q == r or self.devices[q] is None:
                        continue
                    env = Envelope(src=r, dst=q, tag=0, seqn=0, nbytes=0,
                                   wire_dtype="uint8", strm=HB_STRM)
                    try:
                        self.fabric.send(env, b"")
                    except RuntimeError:
                        pass  # peer detached mid-teardown
            now = time.monotonic()
            for dev in self.devices:
                if dev is not None:
                    dev.check_peers(now, self.hb_interval, self.hb_budget)


class EmuDevice(Device):
    """One emulated rank: memory + pool + executor + call worker thread."""

    def __init__(self, ctx: EmuContext, rank: int):
        self.ctx = ctx
        self.rank = rank
        self.mem = DeviceMemory()
        self.pool = RxBufferPool(ctx.nbufs, ctx.bufsize)
        self.comms: dict[int, Communicator] = {}
        self.comm: Communicator | None = None  # world comm (first configured)
        self.executor = MoveExecutor(self.mem, self.pool,
                                     send_fn=ctx.fabric.send,
                                     timeout=DEFAULT_TIMEOUT_S,
                                     window=ctx.pipeline_window,
                                     segment_stream=ctx.segment_stream)
        # ingest cut-through execution: safe here because LocalFabric's
        # send path enqueues without blocking (a jammed receiver falls to
        # its inbox queue), so an inline hop chain can never deadlock
        self.executor.ingest_inline = True
        # observability: tag log lines / flight-recorder dumps with the
        # owning rank, and report pool/executor/plan-cache health through
        # the process-wide registry (Device.register_metrics)
        self.executor.owner_rank = rank
        self.register_metrics(rank)
        self.timeout = DEFAULT_TIMEOUT_S
        self.max_segment_size = DEFAULT_MAX_SEGMENT_SIZE
        self.profiling = False  # armed by the start_profiling config call
        # compiled-plan cache (accl_tpu/plancache.py): relocatable move
        # programs + streamed plan skeletons, keyed per call shape.
        # comm_epoch rides in every key so a reconfigured communicator
        # can never be served a plan built for the old membership.
        self.plan_cache = PlanCache(enabled=ctx.plan_cache)
        self.comm_epoch = 0
        # env read at construction (not import) so tests/embedders can
        # set it after importing the package
        self.chain_depth = max(1, int(os.environ.get(
            "ACCL_TPU_CALL_CHAIN_DEPTH", DEFAULT_CALL_CHAIN_DEPTH)))
        # multi-tenant service (accl_tpu/service): comm -> tenant mapping
        # (fed by configure_communicator) plus this rank's admission
        # controller and resource quotas. The mapping dict is shared BY
        # REFERENCE with the rx pool and the RankService so a late
        # tenant registration is visible everywhere at once.
        self.comm_tenants: dict[int, str] = {}
        # one-sided RMA (accl_tpu/rma): registered windows + the put/get
        # engine. Late-bound getters because soft reset swaps the pool
        # object and config calls change segment size / timeout.
        from ..rma import RmaEngine, WindowRegistry
        self.windows = WindowRegistry(owner=f"emu rank {rank}")
        self.rma = RmaEngine(
            rank, self.mem, self.windows, ctx.fabric.send,
            pool_fn=lambda: self.pool, comm_of=self.comms.get,
            tenant_of=self.tenant_of_comm,
            timeout_fn=lambda: self.timeout,
            seg_fn=lambda: self.max_segment_size, tier="emu",
            csum_fn=lambda: ctx.fabric.csum,
            tuner_fn=lambda: getattr(self, "tuner", None))
        # membership state (armed via ctx.start_heartbeats): peers are
        # tracked once heard from; a dead peer fail-fasts calls on every
        # comm containing it until shrink_communicator rebuilds
        self._peer_last: dict[int, float] = {}
        self._dead_peers: set[int] = set()
        # elastic-membership join handshake (ACCL.grow_communicator):
        # hellos heard per grown comm — {comm_id: {src_grank: signature}}
        # — cleared at configure time (configure_communicator), so the
        # evidence's lifetime is exactly one membership generation
        self._join_cv = threading.Condition()
        self._join_heard: dict[int, dict[int, int]] = {}
        self.service = None
        if ctx.service_config is not None:
            self.service = RankService(
                ctx.service_config, rank=rank,
                tenant_of=self.comm_tenants, pool=self.pool,
                arena=self.executor._arena)
        # cross-call pipelining (chained calls): finishes retire on a
        # dedicated FIFO thread so the call worker can admit the next
        # chained program while the previous one drains
        self._chain_q: queue.Queue | None = None
        self._chain_cv = threading.Condition()
        self._chain_pending = 0
        self._calls: queue.Queue = queue.Queue()
        # submitted-not-yet-retired calls per communicator: the preempt
        # driver bypass may only run a call in the submitting thread
        # when NOTHING of its comm is queued or in flight (program order
        # within a comm is the contract; across comms there is none)
        self._cp_mu = threading.Lock()
        self._comm_pending: dict[int, int] = {}
        # one lock serializes every execution (worker or inline); the
        # inline gate itself lives on the Device base. The counter here
        # covers a call until full RETIREMENT (decrement after _retire).
        self._exec_mu = threading.Lock()
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=f"emu-rank{rank}")
        self._worker.start()
        # dedicated ingress thread: the fabric enqueues without blocking the
        # sender (the reference's emulator wire — ZMQ pub/sub — buffers the
        # same way); only this thread blocks when the rx pool is full
        self._inbox: queue.Queue = queue.Queue()
        self._ing_mu = threading.Lock()
        # deferred-retry wakeup state: _deferred_waiting is set while the
        # ingress loop holds parked messages; a pool release then posts
        # ONE retry token (collapsed while outstanding) so parked
        # messages retry the instant a slot frees, not on a poll tick
        self._deferred_waiting = False
        self._retry_posted = False
        self.pool.on_release = self._on_pool_release
        self._ingress = threading.Thread(target=self._ingress_loop,
                                         daemon=True,
                                         name=f"emu-ingress{rank}")
        self._ingress.start()

    # -- membership (heartbeats; fed by EmuContext._hb_loop) ---------------
    def note_heartbeat(self, grank: int):
        if grank in self._dead_peers:
            self._dead_peers.discard(grank)
        self._peer_last[grank] = time.monotonic()

    def check_peers(self, now: float, interval: float, budget: int):
        for g, last in list(self._peer_last.items()):
            if g in self._dead_peers:
                continue
            age = now - last
            if age > interval:
                from ..tracing import METRICS
                METRICS.inc("heartbeat_missed_total", rank=self.rank,
                            peer=g, tier="emu")
            if age > interval * budget:
                self.note_peer_failed(g)

    def note_peer_failed(self, grank: int):
        """Containment: latch PEER_FAILED on every communicator
        containing the dead peer (per-comm latches — never across
        tenants), fast-abort programs waiting on it, and fail-fast new
        calls on those comms. Communicators excluding the peer (e.g. a
        shrunken survivor comm) are untouched."""
        if grank in self._dead_peers:
            return
        self._dead_peers.add(grank)
        from ..log import get_logger
        from ..tracing import METRICS
        get_logger(__name__).warning(
            "rank %d: peer %d declared dead (missed-heartbeat budget) — "
            "latching PEER_FAILED on its communicators", self.rank, grank,
            extra={"rank": self.rank})
        METRICS.inc("peer_failed_total", rank=self.rank, peer=grank,
                    tier="emu")
        for cid, comm in list(self.comms.items()):
            if any(r.global_rank == grank for r in comm.ranks):
                self.pool.latch_error(cid, int(ErrorCode.PEER_FAILED))
        self.executor.fail_peer(grank, int(ErrorCode.PEER_FAILED))

    # -- elastic membership: join handshake (ACCL.grow_communicator) -------
    def _on_join_frame(self, env):
        """A peer's join hello (strm=JOIN_STRM): tag carries the
        membership signature. Hellos are only ever sent by a rank
        actively inside (or completing) a handshake that FOLLOWED its
        own configuration of the comm — there is deliberately no echo
        from stored state, so stale pre-configure state can never
        satisfy a fresh liveness proof (a completed member's echo for a
        same-signature RE-grow would let a peer finish the bootstrap
        before this rank re-configured, and this rank's configure would
        then wipe the peer's first collective's frames). Receipt is
        liveness evidence — a rejoining rank clears itself from the
        dead set exactly like a resumed heartbeat."""
        if self.rank in self.ctx._hb_killed:
            # a killed rank is a crashed host: it does not process join
            # traffic (kill_rank silences heartbeats; the join lane is
            # liveness-bearing and dies with them)
            return
        self.note_heartbeat(env.src)
        with self._join_cv:
            self._join_heard.setdefault(env.comm_id, {})[env.src] = env.tag
            self._join_cv.notify_all()

    def _send_join(self, comm_id: int, dst_grank: int, sig: int):
        if self.rank in self.ctx._hb_killed:
            return  # crashed hosts send nothing (see _on_join_frame)
        from ..emulator.protocol import JOIN_STRM
        env = Envelope(src=self.rank, dst=dst_grank, tag=sig,
                       seqn=0, nbytes=0, wire_dtype="uint8",
                       strm=JOIN_STRM, comm_id=comm_id)
        try:
            self.ctx.fabric.send(env, b"")
        except (RuntimeError, IndexError):
            # peer not attached (yet), or a global rank outside this
            # fabric's world entirely (the fabric indexes by rank) —
            # either way the resend loop retries and the handshake
            # deadline types the failure as JOIN_FAILED, never a raw
            # fabric exception out of grow_communicator
            pass

    def join_handshake(self, comm: Communicator, timeout: float) -> int:
        """Full-mesh bootstrap barrier of a grown communicator: announce
        ourselves to every peer and wait until every peer has announced
        a MATCHING membership signature. Hellos resend periodically, so
        members may enter at different times; the heard-table restarts
        at CONFIGURE time (configure_communicator), not here, so driver
        retry attempts within one grow share their evidence while a
        re-grow of the same membership must prove liveness afresh. On
        success we broadcast one final COMPLETION hello before
        returning: a peer X could have entered (clearing its table at
        its configure) after our last periodic resend — we only
        complete after hearing X, so the completion hello necessarily
        postdates X's entry and closes that window. A joiner that never
        answers times out with a typed JOIN_FAILED; a peer announcing a
        different membership signature fails fast."""
        sig = comm.membership_signature()
        cid = comm.comm_id
        peers = [r.global_rank for r in comm.ranks
                 if r.global_rank != self.rank]
        if not peers:
            return 0
        deadline = time.monotonic() + max(0.05, timeout)
        tick = min(0.02, max(0.002, timeout / 20.0))
        while True:
            for g in peers:
                self._send_join(cid, g, sig)
            with self._join_cv:
                heard = self._join_heard.get(cid, {})
                if any(g in heard and heard[g] != sig for g in peers):
                    return int(ErrorCode.JOIN_FAILED)
                if all(g in heard for g in peers):
                    break
                self._join_cv.wait(tick)
            if time.monotonic() >= deadline:
                with self._join_cv:
                    heard = self._join_heard.get(cid, {})
                    if any(g in heard and heard[g] != sig
                           for g in peers):
                        return int(ErrorCode.JOIN_FAILED)
                    if not all(g in heard for g in peers):
                        return int(ErrorCode.JOIN_FAILED
                                   | ErrorCode.RECEIVE_TIMEOUT_ERROR)
                break  # complete at the buzzer
        # completion hello, sent 3x: the window-closing message rides an
        # unreliable lane (JOIN frames bypass the retx layer by design),
        # and seeded chaos plans flip a FRESH coin per delivery attempt
        # of the same identity — three sends are three independent loss
        # coins. The residual (every post-peer-configure hello to one
        # peer dropped) is a LIVENESS bound, not a safety hole: the
        # starved peer exhausts its retries and raises typed
        # JOIN_FAILED while this rank's first collective times out —
        # both sides surface, and re-growing recovers (ARCHITECTURE,
        # "Elastic membership").
        for _ in range(3):
            for g in peers:
                self._send_join(cid, g, sig)
        return 0

    def abort_comm(self, comm_id: int, err: int):
        """Revocation containment (ACCL.revoke): async handles already
        in flight on the revoked comm abort with the typed error now —
        never riding out the full recv deadline — and the latched word
        surfaces in any already-posted recv's error path."""
        self.pool.latch_error(comm_id, int(err))
        self.executor.fail_comm(comm_id, int(err))

    # -- reliability / retry hooks -----------------------------------------
    def prepare_retry(self, comm_id: int) -> int:
        """Pre-retry cleanup (driver retry policy): purge the failed
        attempt's stale frames from the rx pool and clear the comm's
        error latch. The retry epoch itself is free — per-peer seqn
        counters advanced fully when the failed attempt was admitted, so
        the re-execution's frames live in a fresh seqn range that stale
        attempt-N traffic can never satisfy."""
        return self.pool.purge_comm(comm_id)

    def rx_capacity(self) -> tuple[int, int]:
        """(nbufs, bufsize) of this rank's rx pool — the preflight
        surface (hierarchical multi-MiB calls want nbufs*bufsize to hold
        at least 2 chunks, see ACCL.preflight)."""
        return (self.ctx.nbufs, self.ctx.bufsize)

    # -- ingress (eager, never blocks the sender) --------------------------
    def ingest(self, env: Envelope, payload: bytes):
        if env.strm >= 2:
            # reliability / one-sided control lanes: heartbeats feed the
            # membership tracker, RMA frames feed the put/get engine
            # (rendezvous payload segments land DIRECTLY in their
            # registered window here — never in the rx pool); anything
            # else (stray ACKs — LocalFabric acks are internal calls) is
            # dropped, never stream-delivered
            from ..emulator.protocol import (HB_STRM, JOIN_STRM,
                                             RMA_DATA_STRM, RMA_STRM)
            if env.strm in (RMA_STRM, RMA_DATA_STRM):
                self.rma.on_frame(env, payload)
            elif env.strm == HB_STRM:
                self.note_heartbeat(env.src)
            elif env.strm == JOIN_STRM:
                self._on_join_frame(env)
            return
        # Fast path: deliver into the pool from the sender's thread — one
        # scheduler handoff less per message, and the ingest-inline
        # cut-through then runs the waiting move right here. Taken even
        # while the inbox holds a backlog: pool matching is exact-seqn so
        # arrival order is irrelevant, try_ingest never claims the LAST
        # spare, and a parked (deferred) message retries the moment a
        # buffer frees — routing a latency tenant's 4 KiB message behind
        # a storm's inbox backlog was a measured millisecond-scale stall.
        # Stream payloads are order-sensitive and always take the queue.
        if not env.strm and self.pool.try_ingest(env, payload):
            return
        self._inbox.put((env, payload))

    def _ingress_loop(self):
        # Deferred delivery: a message that cannot claim a buffer (pool
        # physically full, or its tenant over quota) parks here instead
        # of blocking the loop — one tenant's storm backpressure must
        # never head-of-line-block another tenant's 4 KiB message sitting
        # behind it in the inbox (pool matching is exact-seqn, so
        # out-of-order delivery is safe). Parked messages retry as the
        # pool churns and drop with the typed error word (overflow or
        # TENANT_QUOTA_EXCEEDED) once their deadline expires. The daemon
        # tier keeps blocking ingest: there backpressure rides each
        # peer's own TCP connection, which is real per-peer flow control.
        deferred: collections.deque = collections.deque()
        while True:
            try:
                # coarse timeout only expires parked deadlines; the fast
                # retry wakeup is the pool-release token (_RETRY)
                item = self._inbox.get(timeout=0.05 if deferred else None)
            except queue.Empty:
                item = False
            if item is None:
                return
            if item is _RETRY:
                with self._ing_mu:
                    self._retry_posted = False
            elif item is not False:
                env, payload = item
                if env.strm:
                    self.executor.deliver_stream(env, payload)
                else:
                    got = self.pool.ingest_nowait(env, payload)
                    if got <= 0:
                        deferred.append(
                            (env, payload,
                             time.monotonic() + self.timeout))
            if deferred:
                now = time.monotonic()
                for _ in range(len(deferred)):
                    env, payload, deadline = deferred.popleft()
                    got = self.pool.ingest_nowait(env, payload)
                    if got > 0:
                        continue
                    if now >= deadline:
                        self.pool.latch_ingest_drop(env, got < 0)
                    else:
                        deferred.append((env, payload, deadline))
            with self._ing_mu:
                self._deferred_waiting = bool(deferred)

    def _on_pool_release(self):
        """Pool release listener (consumer threads): wake the ingress
        loop's deferred retry. One token is collapsed while outstanding —
        a release burst costs one queue put, and an idle pool costs
        nothing."""
        with self._ing_mu:
            if not self._deferred_waiting or self._retry_posted:
                return
            self._retry_posted = True
        self._inbox.put(_RETRY)

    # -- Device interface --------------------------------------------------
    def register_buffer(self, buf: ACCLBuffer):
        self.mem.register(buf.address, buf.data)

    def deregister_buffer(self, buf: ACCLBuffer):
        self.mem.deregister(buf.address)

    def configure_communicator(self, comm: Communicator,
                               tenant: str | None = None):
        """Register a communicator (world or split); calls reference it by
        comm_id, like the reference addressing communicator records in
        exchange memory (accl.py:677-708). ``tenant`` groups the comm
        under a service tenant (default: the comm is its own tenant).
        Reconfiguration invalidates the compiled-plan cache (and bumps
        the epoch its keys carry): plans bind comm size/rank numbering at
        expansion time."""
        if comm.comm_id in self.comms:
            # true RE-configuration: its per-peer seqn spaces restart,
            # so retransmission channel state keyed on the old space
            # must not dedup the new one away (fresh comm ids need no
            # reset — and get none, so a racing split can never wipe a
            # sibling rank's in-flight ring). Stranded rx frames and
            # latched error words of the OLD membership die with it too:
            # a grown-back comm must not inherit a stale PEER_FAILED
            # latch (or old-epoch frames) from before the shrink
            self.ctx.fabric.reset_comm(comm.comm_id)
            self.pool.purge_comm(comm.comm_id)
        # join-handshake evidence restarts with the comm's configuration
        # (one membership generation): a RE-grow of the same membership
        # + signature must prove liveness afresh, never inherit the
        # previous handshake's heard-table. Driver retry attempts within
        # ONE grow share the table — they follow one configure.
        with self._join_cv:
            self._join_heard.pop(comm.comm_id, None)
        self.comms[comm.comm_id] = comm
        if tenant:
            self.comm_tenants[comm.comm_id] = tenant
        if self.comm is None:
            self.comm = comm
        self.comm_epoch += 1
        self.plan_cache.invalidate("comm")

    def tenant_of_comm(self, comm_id: int) -> str:
        return tenant_label(comm_id, self.comm_tenants)

    def set_timeout(self, timeout: float):
        self.timeout = timeout
        self.executor.timeout = timeout

    def preferred_segment_size(self) -> int:
        return self.ctx.bufsize

    def topology(self):
        """In-process loopback tier: a hop is a couple of thread handoffs
        plus pool matching (tens of microseconds), bandwidth is memcpy
        through the fabric queues. ``pipeline_depth`` advertises the
        executor's segment-streaming overlap (combine-worker pool) so the
        tuner's segment sizing can use the overlap-aware effective beta;
        a serial/window executor reports 1 (store-and-forward sizing)."""
        from ..tuner.cost import Topology
        ex = self.executor
        # +1: the scheduler thread executes ready moves itself, so even a
        # zero-extra-worker pool overlaps one combine with recv-matching
        depth = (float(ex._n_workers + 1)
                 if ex.window > 0 and ex.segment_stream else 1.0)
        if self.ctx.hosts is not None and len(set(self.ctx.hosts)) > 1:
            # two-tier world: intra figures are this tier's loopback
            # numbers; inter figures are the context's NORMALIZED
            # profile — identical to what the fabric emulates when
            # throttling is armed (a nominally-slower default tier when
            # only the grouping was given: the tuner needs SOME
            # ordering)
            from ..hier import MeshTopology, TierSpec
            outer = tuple(TierSpec(hosts=tuple(h), alpha_us=a, beta_gbps=b)
                          for h, a, b in self.ctx.outer_tiers)
            return MeshTopology.from_hosts(
                self.ctx.hosts, alpha_us=20.0, beta_gbps=4.0,
                inter_alpha_us=self.ctx.inter_alpha_us,
                inter_beta_gbps=self.ctx.inter_beta_gbps,
                tier="emu-n-tier" if outer else "emu-two-tier",
                outer=outer, pipeline_depth=depth)
        return Topology(world_size=self.ctx.world_size, alpha_us=20.0,
                        beta_gbps=4.0, tier="emu", pipeline_depth=depth)

    def push_stream(self, data):
        self.executor.push_stream(data)

    def pop_stream(self, timeout: float = 0.0, count: int | None = None):
        return self.executor.pop_stream_out(timeout, count)

    def set_max_segment_size(self, nbytes: int):
        if nbytes > self.ctx.bufsize:
            raise ValueError(
                f"segment size {nbytes} exceeds rx buffer size "
                f"{self.ctx.bufsize} (reference: segments must fit spare "
                f"buffers, accl.py:660-667)")
        self.max_segment_size = nbytes

    # -- one-sided RMA (accl_tpu/rma) --------------------------------------
    def register_window(self, wid: int, addr: int, nbytes: int):
        self.windows.register(wid, addr, nbytes)

    def deregister_window(self, wid: int):
        self.windows.deregister(wid)

    def poll_notifications(self, window: int, max_records: int = 64):
        """Drain put-with-notify completions — a rank-local dequeue off
        the engine's queue; issues nothing on the wire."""
        return self.rma.notify.poll(window, max_records)

    def _rma_call(self, desc: CallDescriptor,
                  waitfor: Sequence[CallHandle]) -> CallHandle:
        """Launch a put/get: completion is driven by the RMA engine's
        FIN/landing events, not a worker thread — the engine's TX worker
        streams the payload, so an async put overlaps the issuing
        thread's compute. ``waitfor`` chains through done-callbacks."""
        handle = CallHandle(context=desc.scenario.name)
        self._comm_add(desc.comm_id)
        self._inflight_add()
        handle.add_done_callback(
            lambda _err, cid=desc.comm_id: (self._comm_done(cid),
                                            self._inflight_done()))

        def launch():
            comm = self.comms.get(desc.comm_id)
            if comm is None:
                handle.complete(int(ErrorCode.COMM_NOT_CONFIGURED))
                return
            if desc.arithcfg is None:
                handle.complete(int(ErrorCode.ARITHCFG_NOT_CONFIGURED))
                return
            if self._dead_peers and any(r.global_rank in self._dead_peers
                                        for r in comm.ranks):
                handle.complete(int(ErrorCode.PEER_FAILED))
                return
            if desc.scenario == CCLOp.put:
                local = desc.addr_0
                local_c = bool(desc.compression
                               & Compression.OP0_COMPRESSED)
                # addr_2 is free on a put (no result buffer) and carries
                # the notify token; 0 means "no notification requested"
                notify = desc.addr_2 or None
            else:
                local = desc.addr_2
                local_c = bool(desc.compression
                               & Compression.RES_COMPRESSED)
                notify = None
            self.rma.start(
                desc.scenario, comm, desc.root_src_dst, desc.tag,
                desc.addr_1, desc.count, desc.arithcfg,
                bool(desc.compression & _ETH_C), local, handle,
                tenant=self.tenant_of_comm(desc.comm_id),
                local_compressed=local_c, notify=notify)

        waitfor = tuple(waitfor)
        if not waitfor:
            launch()
            return handle
        remaining = [len(waitfor)]
        mu = threading.Lock()

        def dep_done(err):
            if err and not handle.done():
                handle.complete(int(err))
                return
            with mu:
                remaining[0] -= 1
                fire = remaining[0] == 0
            if fire and not handle.done():
                launch()

        for dep in waitfor:
            dep.add_done_callback(dep_done)
        return handle

    def call_async(self, desc: CallDescriptor,
                   waitfor: Sequence[CallHandle] = (), *,
                   inline_ok: bool = False) -> CallHandle:
        if desc.scenario in (CCLOp.put, CCLOp.get):
            return self._rma_call(desc, waitfor)
        handle = CallHandle(context=desc.scenario.name)
        waitfor = tuple(waitfor)
        first = self._comm_add(desc.comm_id)
        # Inline fast path: a synchronous call on an idle device retires
        # in the caller's thread, skipping two scheduler handoffs (~2x
        # lower small-message latency). Service-eligible data calls still
        # ROUTE THROUGH the service here (admission accounting + no
        # _exec_mu hold across the collective — see _retire); with an
        # idle controller the express grant keeps the one-thread shape.
        if inline_ok and self._inline_begin(waitfor):
            deferred = False
            try:
                deferred = self._retire(desc, waitfor, handle,
                                        sync_express=True)
            finally:
                if not deferred:
                    self._comm_done(desc.comm_id)
                    self._inflight_done()
            return handle
        self._inflight_add()
        if first and not waitfor and self._service_eligible(desc):
            # driver bypass: a service call with nothing of its comm in
            # flight submits from THIS thread — the call-worker queue
            # handoff is an OS wake per call; per-comm program order is
            # safe because nothing of this comm is queued or in flight.
            # The controller decides express (admit+finish here, bounded
            # by the call; sync callers only) vs queued (returns
            # immediately, the handle completes on the tenant's finish
            # worker).
            deferred = False
            try:
                deferred = self._retire(desc, waitfor, handle,
                                        sync_express=inline_ok)
            finally:
                if not deferred:
                    self._comm_done(desc.comm_id)
                    self._inflight_done()
            return handle
        self._calls.put((desc, waitfor, handle))
        return handle

    def _comm_add(self, comm_id: int) -> bool:
        """Count one submitted call against its comm; True = it is the
        only one in flight for that comm."""
        with self._cp_mu:
            n = self._comm_pending.get(comm_id, 0)
            self._comm_pending[comm_id] = n + 1
            return n == 0

    def _comm_done(self, comm_id: int):
        with self._cp_mu:
            n = self._comm_pending.get(comm_id, 1) - 1
            if n > 0:
                self._comm_pending[comm_id] = n
            else:
                self._comm_pending.pop(comm_id, None)


    def soft_reset(self):
        """Drain the rx pool and zero sequence counters.

        Parity: encore_soft_reset (c:1133-1136). Like the reference's reset,
        this is rank-local state surgery: it must be performed on EVERY rank
        of the fabric (each host resets its own CCLO) or sequence numbers
        desynchronize from peers' outbound counters.
        """
        self.pool = RxBufferPool(self.ctx.nbufs, self.ctx.bufsize)
        self.pool.on_release = self._on_pool_release
        self.executor.pool = self.pool
        self.executor.reset_streams()
        # in-flight one-sided transfer state dies with the seqn spaces
        # (window REGISTRATIONS survive — they are configuration, like
        # communicators)
        self.rma.reset()
        if self.service is not None:
            self.service.wire_pool(self.pool)
        # retransmission channels keyed on the zeroed seqn spaces reset
        # with them (the fabric latch closure reads self.pool — current)
        self.ctx.fabric.reset_rank(self.rank)
        for comm in self.comms.values():
            for r in comm.ranks:
                r.inbound_seq = r.outbound_seq = 0

    def deinit(self):
        self._calls.put(None)
        self._inbox.put(None)
        with self._chain_cv:
            if self._chain_q is not None:
                self._chain_q.put(None)
        if self.service is not None:
            self.service.close()
        self.rma.close()
        self.windows.close()
        self.executor.close()
        self.ctx.note_device_deinit()

    # -- worker ------------------------------------------------------------
    def _run(self):
        while True:
            item = self._calls.get()
            if item is None:
                return
            desc, waitfor, handle = item
            deferred = False
            try:
                deferred = self._retire(desc, waitfor, handle)
            finally:
                if not deferred:
                    self._comm_done(desc.comm_id)
                    self._inflight_done()

    def _retire(self, desc: CallDescriptor, waitfor,
                handle: CallHandle, allow_service: bool = True,
                sync_express: bool = False) -> bool:
        """Wait dependencies, execute, complete the handle — never raises
        (errors land in the handle). Returns True when the call was
        DEFERRED — admitted through the service layer or as a chained
        program: the handle (and this device's in-flight accounting)
        then retires on the service/chain finish thread, after the
        program drains. ``sync_express`` marks a synchronous caller
        running in its own (driver) thread: the service may then grant
        express admission, running the whole call here — an async
        submitter (or the shared call worker) must never block through a
        collective, so only sync driver-thread calls opt in."""
        try:
            for dep in waitfor:
                dep.wait(self.timeout)
            if self._dead_peers \
                    and desc.scenario not in (CCLOp.config, CCLOp.nop):
                comm = self.comms.get(desc.comm_id)
                if comm is not None and any(
                        r.global_rank in self._dead_peers
                        for r in comm.ranks):
                    # fail-fast BEFORE service admission too: an admitted
                    # program over a dead member would only burn workers
                    # until its recv deadline
                    handle.complete(int(ErrorCode.PEER_FAILED))
                    return False
            if allow_service and self._service_eligible(desc):
                # The service path runs ENTIRELY outside _exec_mu: the
                # controller has its own lock, per-comm program order is
                # fixed by the submitting thread (worker FIFO, or the
                # driver bypass gated on nothing-of-this-comm-in-flight),
                # and an express grant may BLOCK this thread until the
                # collective drains. Holding _exec_mu across that wait
                # deadlocks multi-tenant worlds: rank A's tenant-X call
                # holds the device exclusive while waiting on rank B,
                # whose tenant-X call queues behind rank B's exclusive
                # held by tenant Y, waiting back on rank A's tenant-Y —
                # a cycle of the legacy serialization the service layer
                # exists to break. (Also: plan preparation is
                # milliseconds for storm-sized programs — off the lock.)
                comm = self.comms[desc.comm_id]
                prep = (comm, self._prepare_program(desc, comm))
                self._try_service(desc, handle, prep, sync_express)
                return True
            with self._exec_mu:
                if self._try_chain(desc, handle):
                    return True
                # a non-service, non-chained call must observe every
                # deferred predecessor fully retired (execution
                # serialization and handle-completion order are the
                # existing per-comm contract). Data-shaped calls (e.g.
                # stream-flagged) drain THEIR comm only — a global drain
                # would park them behind an unrelated tenant's endless
                # storm; config/reset calls apply to a quiesced device
                # and keep the conservative full drain.
                self._drain_service(
                    None if desc.scenario in (CCLOp.config, CCLOp.nop)
                    else desc.comm_id)
                self._drain_chain()
                self._last_move_stats = None
                err = self._execute(desc)
                stats = self._last_move_stats
            if stats is not None:
                # pipeline counters for the profiler (CallRecord fields);
                # set before complete() so done-callbacks observe them
                handle.pipeline_stats = stats
            handle.complete(err)
        except ACCLError as exc:
            # failed waitfor dependency: propagate its error word
            handle.complete(exc.error_word, exception=exc)
        except TimeoutError as exc:
            handle.complete(int(ErrorCode.RECEIVE_TIMEOUT_ERROR),
                            exception=exc)
        except Exception as exc:  # noqa: BLE001 — report, don't kill worker
            handle.complete(int(ErrorCode.INVALID_CALL), exception=exc)
        return False

    # -- multi-tenant service admission (accl_tpu/service) -----------------
    def _service_eligible(self, desc: CallDescriptor) -> bool:
        """Data calls the admission layer can route: streamed executor,
        non-stream shape (stream ports are executor-global state — two
        tenants' concurrent programs would interleave entries), known
        communicator."""
        svc = self.service
        ex = self.executor
        if svc is None or not (ex.window > 0 and ex.segment_stream):
            return False
        if desc.scenario in (CCLOp.config, CCLOp.nop):
            return False
        if desc.stream_flags != StreamFlags.NO_STREAM:
            return False
        return (self.comms.get(desc.comm_id) is not None
                and desc.arithcfg is not None)

    def _try_service(self, desc: CallDescriptor, handle: CallHandle,
                     prep, sync_express: bool = False) -> bool:
        """Route a data call through the tenant-aware admission layer:
        the program was prepared by the submitting thread (per-comm
        program order is fixed by the tenant queue) and is admitted to
        the streamed executor when the DWRR scheduler grants it —
        programs of independent communicators drain concurrently;
        same-comm programs keep the serialize-unless-chained contract.
        Runs WITHOUT ``_exec_mu`` (see _retire: an express grant blocks
        this thread until the collective drains, and a device-exclusive
        hold across that wait deadlocks multi-tenant worlds). The handle
        completes on the tenant's finish worker (FIFO per tenant), or in
        this thread on an express grant."""
        svc = self.service
        ex = self.executor
        comm, (moves, skeleton, meta) = prep
        tenant = self.tenant_of_comm(desc.comm_id)
        nbytes = desc.count * desc.arithcfg.uncompressed_elem_bytes
        # admission cost in rx-buffer-sized units: weighted fairness is
        # byte-weighted, so a 16 MiB storm program spends ~256 units of
        # deficit where a 4 KiB call spends 1 — the small-call tenant's
        # queue drains hundreds of calls per storm grant
        cost = max(1.0, nbytes / max(1, self.ctx.bufsize))
        # a preempt tenant jumps the queue at ADMISSION and at worker
        # DISPATCH (executor._pick_prog_locked) — both under the same
        # knob; nothing is ever preempted mid-move
        priority = 1 if (svc.config.preempt_admission
                         and svc.config.spec_of(tenant).preempt) else 0

        # trace tracks carry only EXPLICIT tenant groupings (the per-comm
        # default would rename every single-app trace's lanes)
        trace_tenant = self.comm_tenants.get(desc.comm_id, "")

        def admit():
            return ex.begin_streamed(moves, desc.arithcfg, comm,
                                     skeleton=skeleton, tenant=tenant,
                                     priority=priority,
                                     trace_tenant=trace_tenant)

        def finish(prog, exc):
            try:
                if exc is None:
                    try:
                        err, stats = ex.finish_streamed(prog)
                        handle.pipeline_stats = dict(stats, **meta)
                        handle.complete(err)
                        return
                    except Exception as e:  # noqa: BLE001 — surface
                        exc = e
                handle.complete(
                    int(ErrorCode.INVALID_CALL),
                    exception=exc if isinstance(exc, Exception) else None)
            finally:
                self._comm_done(desc.comm_id)
                self._inflight_done()

        # express only for a synchronous driver-thread caller AND a fully
        # streamed program: a barrier move would park the admitting
        # thread mid-feed until the program drains
        express_ok = sync_express and all(
            st.eligible or st.fused for st in skeleton.steps)
        svc.controller.submit(tenant, cost, admit, finish,
                              comm_id=desc.comm_id, chain=desc.chain,
                              express_ok=express_ok)
        return True

    def _drain_service(self, comm_id: int | None = None):
        """Block until service-admitted programs retired — of ONE comm
        when given (the per-comm ordering contract's bounded wait), of
        every tenant otherwise (config/reset quiescence)."""
        if self.service is not None:
            if comm_id is None:
                self.service.controller.drain()
            else:
                self.service.controller.drain_comm(comm_id)

    # -- cross-call pipelining (chained calls) -----------------------------
    def _try_chain(self, desc: CallDescriptor, handle: CallHandle) -> bool:
        """Admit a chain-hinted call into the streamed executor WITHOUT
        waiting for it (or its predecessors) to drain. Only a compiled-
        plan cache HIT qualifies — a miss pays expansion anyway, so it
        takes the ordinary path (which populates the cache for the next
        link). Caller holds ``_exec_mu``."""
        if not desc.chain or desc.scenario in (CCLOp.config, CCLOp.nop):
            return False
        ex = self.executor
        if not (ex.window > 0 and ex.segment_stream
                and self.plan_cache.enabled):
            return False
        comm = self.comms.get(desc.comm_id)
        if comm is None or desc.arithcfg is None:
            return False
        got = cached_program(self.plan_cache, compile_missing=False,
                             tuner=self.tuner, streamed=True,
                             **self._cache_args(desc, comm))
        if got is None or got[1] is None:
            return False  # miss (or no skeleton): ordinary path
        moves, skeleton, _state, expand_us, _plan_us = got
        # bound admission depth: each in-flight program parks its inbound
        # messages in the (finite) rx pool until consumed, so an unbounded
        # chain would overflow eager ingress
        with self._chain_cv:
            while self._chain_pending >= self.chain_depth:
                self._chain_cv.wait()
            if self._chain_q is None:
                self._chain_q = queue.Queue()
                threading.Thread(target=self._chain_loop, daemon=True,
                                 name=f"emu-chain{self.rank}").start()
            self._chain_pending += 1
        try:
            meta = {"expand_us": round(expand_us, 1),
                    "plan_us": 0.0, "plan_cache": "hit"}
            prog = ex.begin_streamed(moves, desc.arithcfg, comm,
                                     skeleton=skeleton)
            self._chain_q.put((prog, handle, meta, desc.comm_id))
        except BaseException:
            # admission failed (executor closing, ...): the pending slot
            # must be returned or _drain_chain deadlocks the call worker
            with self._chain_cv:
                self._chain_pending -= 1
                self._chain_cv.notify_all()
            raise
        return True

    def _chain_loop(self):
        """FIFO retirement of chained programs: completion order follows
        admission order, so chained handles observe the same ordering
        contract as queued calls."""
        while True:
            item = self._chain_q.get()
            if item is None:
                return
            prog, handle, meta, comm_id = item
            try:
                err, stats = self.executor.finish_streamed(prog)
                handle.pipeline_stats = dict(stats, **meta)
                handle.complete(err)
            except Exception as exc:  # noqa: BLE001 — keep retiring
                handle.complete(int(ErrorCode.INVALID_CALL), exception=exc)
            finally:
                self._comm_done(comm_id)
                self._inflight_done()
                with self._chain_cv:
                    self._chain_pending -= 1
                    self._chain_cv.notify_all()

    def _drain_chain(self):
        """Block until every admitted chained program has retired."""
        with self._chain_cv:
            while self._chain_pending:
                self._chain_cv.wait()

    def _execute(self, desc: CallDescriptor) -> int:
        if desc.scenario == CCLOp.nop:
            return 0
        if desc.scenario == CCLOp.config:
            return self.apply_config(desc)  # shared dispatch (Device base)
        comm = self.comms.get(desc.comm_id)
        if comm is None:
            return int(ErrorCode.COMM_NOT_CONFIGURED)
        if desc.arithcfg is None:
            return int(ErrorCode.ARITHCFG_NOT_CONFIGURED)
        return self._execute_data(desc, comm)

    def segment_size_bound(self) -> int | None:
        return self.ctx.bufsize  # segments must fit rx buffers

    def _streamed_engine(self) -> bool:
        ex = self.executor
        return ex.window > 0 and ex.segment_stream

    def _cache_args(self, desc: CallDescriptor, comm: Communicator) -> dict:
        """The :func:`~accl_tpu.plancache.cached_program` arguments this
        descriptor maps to (shared by the execute and chained-admission
        paths so their keys can never drift)."""
        return dict(
            scenario=desc.scenario, count=desc.count,
            world_size=comm.size, local_rank=comm.local_rank,
            arithcfg=desc.arithcfg,
            max_segment_size=self.max_segment_size,
            comm_id=desc.comm_id, comm_epoch=self.comm_epoch,
            root_src_dst=desc.root_src_dst, func=desc.function,
            tag=desc.tag, bases=(desc.addr_0, desc.addr_1, desc.addr_2),
            compression=desc.compression, stream=desc.stream_flags,
            algorithm=desc.algorithm, counts=desc.counts,
            tenant=self.tenant_of_comm(desc.comm_id))

    def _prepare_program(self, desc: CallDescriptor, comm: Communicator):
        """Produce this call's move program through the one shared
        preparation path (plancache.cached_program): a cache hit only
        rebinds addresses (and the executor rebases wire seqns); a miss
        expands once against symbolic bases and caches the result;
        cache-disabled runs expand fresh. Returns
        (moves, skeleton-or-None, CallRecord plan-cache meta)."""
        moves, skeleton, state, expand_us, plan_us = cached_program(
            self.plan_cache, tuner=self.tuner,
            streamed=self._streamed_engine(),
            **self._cache_args(desc, comm))
        return moves, skeleton, {
            "expand_us": round(expand_us, 1),
            "plan_us": round(plan_us, 1), "plan_cache": state}

    def _execute_data(self, desc: CallDescriptor, comm: Communicator) -> int:
        if getattr(comm, "revoked", False):
            # a call that was queued before the application revoked the
            # comm must fail fast and typed, like the in-flight programs
            # abort_comm unwound — not discover the revocation by
            # burning its recv deadline
            return int(ErrorCode.PEER_FAILED)
        if self._dead_peers and any(r.global_rank in self._dead_peers
                                    for r in comm.ranks):
            # fail-fast containment: a collective over a dead member can
            # only burn its deadline — surface PEER_FAILED immediately;
            # comms excluding the peer (shrunken survivors) run normally
            return int(ErrorCode.PEER_FAILED)
        moves, skeleton, meta = self._prepare_program(desc, comm)
        err = self.executor.execute(
            moves, desc.arithcfg, comm, skeleton=skeleton,
            tenant=self.tenant_of_comm(desc.comm_id),
            trace_tenant=self.comm_tenants.get(desc.comm_id, ""))
        self._last_move_stats = dict(self.executor.last_stats, **meta)
        return err
