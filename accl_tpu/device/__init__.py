"""Device backends: where calls execute.

* :class:`~accl_tpu.device.emu.EmuContext` / ``EmuDevice`` — in-process
  threaded CPU emulator (loopback fabric).
* ``SimDevice`` (sim.py) — client to an out-of-process rank daemon over a
  framed-TCP socket (reference: SimDevice over ZMQ, accl.py:106-159).
* ``TpuDevice`` (tpu.py) — in-process SPMD backend over a jax Mesh; the
  production path.
"""

from .base import Device
from .emu import EmuContext, EmuDevice

__all__ = ["Device", "EmuContext", "EmuDevice"]
