"""TPU backend: the ACCL call surface executed on a jax device mesh.

Architecture (the survey's "hard part (a)" — two-sided semantics on an SPMD
substrate): one process is the SPMD controller of all ranks (standard JAX).
Each rank still gets its own ``TpuDevice`` view + ``ACCL`` driver instance,
so the same rank-parallel test corpus drives every tier. Cross-rank
coordination happens in a host-side rendezvous:

* **Collectives** rendezvous all member ranks' calls (matched in per-rank
  program order, MPI semantics); the last arriving rank executes ONE
  shard_map program over the mesh (MeshCollectives) and scatters results
  into every rank's buffer.
* **send** is eager: the payload is snapshotted and the call completes
  (reference parity: eager ingress lets send finish before recv posts).
  **recv** matches pending sends by ``(comm, src, dst, tag)`` + sequence
  order; the host rendezvous IS the transfer on this tier (tagged
  transfers that must ride ICI belong inside a jitted program via
  ``MeshCollectives.exchange`` / ``send_recv``).

This driver-compat layer stages through host numpy mirrors, which costs
host<->device copies per call — it exists for API parity and the test
corpus. The *performance* path is using :class:`MeshCollectives` (or
`accl_tpu.parallel` inside your own pjit/shard_map programs) directly on
jax.Arrays; bench.py measures that path, and
``benchmarks/driver_overhead.py`` quantifies the tier gap (measured on
the 8-vdev CPU mesh: ~5x per 64Ki-element allreduce call, ~2 ms of host
staging vs the direct cached program).
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Sequence

import numpy as np

import jax.numpy as jnp

from ..buffer import ACCLBuffer
from ..call import CallDescriptor, CallHandle
from ..communicator import Communicator
from ..constants import (CCLOp, CollectiveAlgorithm, Compression,
                         DEFAULT_MAX_SEGMENT_SIZE, DEFAULT_TIMEOUT_S,
                         ErrorCode, check_algorithm)
from ..emulator.executor import DeviceMemory
from ..parallel.collectives import MeshCollectives
from ..parallel.mesh import make_mesh
from ..parallel.tree import Tree2DCollectives
from .base import Device


def _factor_2d(w: int) -> tuple[int, int]:
    """Largest divisor pair (outer, inner) with outer <= inner — the 2D
    mesh shape the tree collectives ride. (1, w) means no 2D structure."""
    o = int(w ** 0.5)
    while o > 1 and w % o:
        o -= 1
    return o, w // o

_COLLECTIVES = {CCLOp.bcast, CCLOp.scatter, CCLOp.gather, CCLOp.reduce,
                CCLOp.allgather, CCLOp.allreduce, CCLOp.reduce_scatter,
                CCLOp.alltoall, CCLOp.barrier}


class TpuContext:
    """Shared state of an N-rank TPU-backed world (single SPMD controller)."""

    def __init__(self, world_size: int | None = None, mesh=None,
                 axis_name: str = "rank", platform: str | None = None,
                 algorithm: str = "xla"):
        if mesh is None:
            mesh = make_mesh((world_size,) if world_size else None,
                             (axis_name,), platform=platform)
        self.mesh = mesh
        self.axis_name = axis_name
        self.world_size = mesh.shape[axis_name]
        self.coll = MeshCollectives(mesh, axis_name)
        self._subcolls: dict[int, MeshCollectives] = {}
        self._subtrees: dict[int, Tree2DCollectives | None] = {}
        self.tree = self._make_tree(
            list(np.asarray(mesh.devices).reshape(-1)))
        self.algorithm = algorithm
        self.devices: list[TpuDevice | None] = [None] * self.world_size
        # rendezvous state
        self._lock = threading.Condition()
        # (comm_id, op_index) -> {comm-local rank: desc}
        self._pending: dict[tuple, dict[int, CallDescriptor]] = {}
        # keys claimed by a launcher, execution in flight (result coming)
        self._claimed: set[tuple] = set()
        # (comm_id, op_index) -> [error_word, readers_remaining]
        self._results: dict[tuple, list[int]] = {}
        # (comm_id, src_g, dst_g) -> deque of (tag, payload ndarray)
        self._sends: dict[tuple, collections.deque] = \
            collections.defaultdict(collections.deque)

    def device(self, rank: int) -> "TpuDevice":
        if self.devices[rank] is None:
            self.devices[rank] = TpuDevice(self, rank)
        return self.devices[rank]

    @staticmethod
    def _make_tree(devs) -> Tree2DCollectives | None:
        """Hierarchical collectives over the same devices folded into the
        largest 2D factorization — the bandwidth-correct path for rooted
        ops at scale (BASELINE config 4's 32-rank (8,4) trees). None when
        the world has no 2D structure (prime or < 4 ranks)."""
        from jax.sharding import Mesh
        o, i = _factor_2d(len(devs))
        if o < 2:
            return None
        return Tree2DCollectives(
            Mesh(np.asarray(devs).reshape(o, i), ("outer", "inner")))

    def _comm_devices(self, comm: Communicator) -> list:
        """The communicator's devices in comm-local rank order (one
        rank->device convention for every sub-mesh built from the world)."""
        world_devs = list(np.asarray(self.mesh.devices).reshape(-1))
        return [world_devs[r.global_rank] for r in comm.ranks]

    def coll_for(self, comm: Communicator) -> MeshCollectives:
        """Collectives bound to the communicator's sub-mesh: member global
        ranks select their devices from the world mesh (a split comm runs
        over its own axis, so axis_index == comm-local rank). Cache fills
        take the ctx lock — launchers of disjoint comms run concurrently."""
        if comm.size == self.world_size:
            return self.coll
        key = comm.comm_id
        with self._lock:
            cached = self._subcolls.get(key)
        if cached is not None:
            return cached
        from jax.sharding import Mesh
        sub = MeshCollectives(
            Mesh(np.asarray(self._comm_devices(comm)), (self.axis_name,)),
            self.axis_name)
        with self._lock:
            return self._subcolls.setdefault(key, sub)

    def tree_for(self, comm: Communicator) -> Tree2DCollectives | None:
        """The communicator's 2D tree context (None when its size has no
        2D factorization)."""
        if comm.size == self.world_size:
            return self.tree
        key = comm.comm_id
        with self._lock:
            if key in self._subtrees:
                return self._subtrees[key]
        tree = self._make_tree(self._comm_devices(comm))
        with self._lock:
            return self._subtrees.setdefault(key, tree)


class TpuDevice(Device):
    """One rank's view of the TPU-backed world."""

    def __init__(self, ctx: TpuContext, rank: int):
        self.ctx = ctx
        self.rank = rank
        self.mem = DeviceMemory()          # host mirrors of device buffers
        self.comms: dict[int, Communicator] = {}
        self.comm: Communicator | None = None
        self.timeout = DEFAULT_TIMEOUT_S
        self.max_segment_size = DEFAULT_MAX_SEGMENT_SIZE
        self.profiling = False  # armed by the start_profiling config call
        self._coll_index: dict[int, int] = collections.defaultdict(int)
        self._calls: queue.Queue = queue.Queue()
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=f"tpu-rank{rank}")
        self._worker.start()

    # -- Device interface --------------------------------------------------
    def register_buffer(self, buf: ACCLBuffer):
        self.mem.register(buf.address, buf.data)

    def deregister_buffer(self, buf: ACCLBuffer):
        self.mem.deregister(buf.address)

    def configure_communicator(self, comm: Communicator):
        self.comms[comm.comm_id] = comm
        if self.comm is None:
            self.comm = comm

    def set_timeout(self, timeout: float):
        self.timeout = timeout

    def set_max_segment_size(self, nbytes: int):
        self.max_segment_size = nbytes

    def call_async(self, desc: CallDescriptor,
                   waitfor: Sequence[CallHandle] = (), *,
                   inline_ok: bool = False) -> CallHandle:
        # inline_ok unused: the rendezvous already runs the collective in
        # whichever rank's thread completes the group (outside the lock)
        handle = CallHandle(context=desc.scenario.name)
        self._calls.put((desc, tuple(waitfor), handle))
        return handle

    def soft_reset(self):
        with self.ctx._lock:
            self.ctx._sends.clear()
        self._coll_index.clear()

    def deinit(self):
        self._calls.put(None)

    # -- worker ------------------------------------------------------------
    def _run(self):
        from ..constants import ACCLError
        while True:
            item = self._calls.get()
            if item is None:
                return
            desc, waitfor, handle = item
            try:
                for dep in waitfor:
                    dep.wait(self.timeout)
                handle.complete(self._execute(desc))
            except ACCLError as exc:
                handle.complete(exc.error_word, exception=exc)
            except TimeoutError as exc:
                handle.complete(int(ErrorCode.RECEIVE_TIMEOUT_ERROR),
                                exception=exc)
            except Exception as exc:  # noqa: BLE001
                handle.complete(int(ErrorCode.INVALID_CALL), exception=exc)

    # -- operand staging ---------------------------------------------------
    def _read_operand(self, addr: int, count: int, desc, which: Compression
                      ) -> np.ndarray:
        cfg = desc.arithcfg
        stored = (cfg.compressed_dtype if desc.compression & which
                  else cfg.uncompressed_dtype)
        return self.mem.read(addr, count, stored).astype(
            cfg.uncompressed_dtype, copy=False)

    def _write_result(self, addr: int, data: np.ndarray, desc):
        cfg = desc.arithcfg
        out = (cfg.compressed_dtype
               if desc.compression & Compression.RES_COMPRESSED
               else cfg.uncompressed_dtype)
        self.mem.write(addr, np.asarray(data, dtype=out))

    # -- execution ---------------------------------------------------------
    def _execute(self, desc: CallDescriptor) -> int:
        op = desc.scenario
        if op == CCLOp.nop:
            return 0
        if op == CCLOp.config:
            return self.apply_config(desc)  # shared dispatch (Device base)
        if desc.stream_flags:
            # no host-side stream port on this tier: a streamed operand or
            # result belongs INSIDE the jitted program (fuse the producer/
            # consumer with the collective). Reject explicitly rather than
            # silently executing a memory-only variant.
            return int(ErrorCode.STREAM_NOT_SUPPORTED)
        comm = self.comms.get(desc.comm_id)
        if comm is None:
            return int(ErrorCode.COMM_NOT_CONFIGURED)
        if op == CCLOp.copy:
            data = self._read_operand(desc.addr_0, desc.count, desc,
                                      Compression.OP0_COMPRESSED)
            self._write_result(desc.addr_2, data, desc)
            return 0
        if op == CCLOp.combine:
            from ..emulator.executor import _REDUCERS
            a = self._read_operand(desc.addr_0, desc.count, desc,
                                   Compression.OP0_COMPRESSED)
            b = self._read_operand(desc.addr_1, desc.count, desc,
                                   Compression.OP1_COMPRESSED)
            self._write_result(desc.addr_2, _REDUCERS[desc.function](a, b),
                               desc)
            return 0
        if op == CCLOp.send:
            return self._do_send(desc, comm)
        if op == CCLOp.recv:
            return self._do_recv(desc, comm)
        if op in _COLLECTIVES:
            return self._do_collective(desc, comm)
        return int(ErrorCode.COLLECTIVE_NOT_IMPLEMENTED)

    # -- send/recv rendezvous ---------------------------------------------
    def _do_send(self, desc: CallDescriptor, comm: Communicator) -> int:
        payload = self._read_operand(desc.addr_0, desc.count, desc,
                                     Compression.OP0_COMPRESSED)
        if desc.compression & Compression.ETH_COMPRESSED:
            payload = payload.astype(desc.arithcfg.compressed_dtype)
        dst_g = comm.ranks[desc.root_src_dst].global_rank
        key = (desc.comm_id, comm.my_global_rank, dst_g)
        with self.ctx._lock:
            self.ctx._sends[key].append((desc.tag, payload))
            self.ctx._lock.notify_all()
        return 0

    def _match_send(self, key: tuple, tag: int):
        """Pop the oldest pending send matching ``tag`` (TAG_ANY semantics
        identical to the emulator's RxBufferPool._match). Caller holds the
        ctx lock."""
        from ..constants import TAG_ANY
        pending = self.ctx._sends.get(key)
        if not pending:
            return None
        for i, (stag, payload) in enumerate(pending):
            if tag == TAG_ANY or stag == tag or stag == TAG_ANY:
                del pending[i]
                if not pending:
                    del self.ctx._sends[key]
                return payload
        return None

    def _do_recv(self, desc: CallDescriptor, comm: Communicator) -> int:
        import time
        src_g = comm.ranks[desc.root_src_dst].global_rank
        me_g = comm.my_global_rank
        key = (desc.comm_id, src_g, me_g)
        deadline = time.monotonic() + self.timeout
        with self.ctx._lock:
            while True:
                payload = self._match_send(key, desc.tag)
                if payload is not None:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self.ctx._lock.wait(remaining):
                    return int(ErrorCode.RECEIVE_TIMEOUT_ERROR)
        if payload.size != desc.count:
            # emulator-tier parity: envelope length must match the posted
            # receive exactly (DMA_MISMATCH_ERROR, executor._fetch)
            return int(ErrorCode.DMA_MISMATCH_ERROR)
        # The transfer itself is the host-side rendezvous above: this
        # driver tier stages per call (module docstring), so the payload
        # is already host-visible when matched — a ppermute here would be
        # a decorative device round-trip, not a data path. Programs that
        # need tagged transfers to ride ICI use ``MeshCollectives.
        # exchange`` / ``send_recv`` inside their own jitted program,
        # where the payload genuinely lives device-side.
        received = payload.astype(desc.arithcfg.uncompressed_dtype)
        self._write_result(desc.addr_2, received, desc)
        return 0

    # -- collective rendezvous --------------------------------------------
    def _do_collective(self, desc: CallDescriptor, comm: Communicator) -> int:
        import time
        idx = self._coll_index[desc.comm_id]
        self._coll_index[desc.comm_id] += 1
        key = (desc.comm_id, idx)
        ctx = self.ctx
        with ctx._lock:
            group = ctx._pending.setdefault(key, {})
            group[comm.local_rank] = desc
            is_last = len(group) == comm.size
            if is_last:
                # claim the group; execution happens OUTSIDE the lock so
                # collectives of disjoint communicators run concurrently
                # (jit/dispatch time would otherwise serialize the world)
                del ctx._pending[key]
                ctx._claimed.add(key)
        if is_last:
            # the publish runs in a finally so a claimed key ALWAYS resolves
            # — waiters in the claimed state deliberately never time out, so
            # any escape path (desc-assembly errors, BaseExceptions) that
            # skipped publication would wedge them forever
            err = int(ErrorCode.INVALID_CALL)
            try:
                descs = [group[r] for r in range(comm.size)]
                err = self._launch(descs, comm)
            except Exception:  # noqa: BLE001
                import traceback
                traceback.print_exc()  # observability: don't bury the cause
            finally:
                with ctx._lock:
                    ctx._claimed.discard(key)
                    if comm.size > 1:
                        # [error, readers remaining]; deleted when drained
                        ctx._results[key] = [err, comm.size - 1]
                    ctx._lock.notify_all()
            return err
        deadline = time.monotonic() + self.timeout
        with ctx._lock:
            while key not in ctx._results:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    if key in ctx._claimed:
                        # execution in flight: the launcher WILL publish
                        # (exceptions included), so departing now would
                        # return a bogus timeout for a call that completes
                        # and leave an undrainable result entry behind —
                        # keep waiting for the publication instead
                        ctx._lock.wait(1.0)
                        continue
                    # group still incomplete: abandon our slot
                    pend = ctx._pending.get(key)
                    if pend is not None:
                        pend.pop(comm.local_rank, None)
                    return int(ErrorCode.RECEIVE_TIMEOUT_ERROR)
                ctx._lock.wait(remaining)
            entry = ctx._results[key]
            entry[1] -= 1
            if entry[1] <= 0:
                del ctx._results[key]
            return entry[0]

    def _launch(self, descs: list, comm: Communicator) -> int:
        """Execute one collective for all member ranks (no locks held)."""
        ctx = self.ctx
        d0 = descs[0]
        op = d0.scenario
        if any(d.scenario != op or d.count != d0.count for d in descs):
            return int(ErrorCode.INVALID_CALL)
        count = d0.count
        W = comm.size
        cfg = d0.arithcfg
        wire = (cfg.compressed_dtype
                if d0.compression & Compression.ETH_COMPRESSED else None)
        devs = [ctx.devices[comm.ranks[r].global_rank] for r in range(W)]

        def read_all(addr_of, n):
            rows = []
            for r, d in enumerate(descs):
                addr = addr_of(d)
                if addr:
                    rows.append(devs[r]._read_operand(
                        addr, n, d, Compression.OP0_COMPRESSED))
                else:
                    rows.append(np.zeros(n, cfg.uncompressed_dtype))
            return rows

        coll, alg = ctx.coll_for(comm), ctx.algorithm
        # per-call selector (CollectiveAlgorithm) overrides the context
        # default: ring variants lower to the shard_map ppermute rings,
        # everything else to XLA's native collectives. Validation uses the
        # same table as the emulator tiers so invalid (op, algorithm) pairs
        # fail identically everywhere.
        try:
            check_algorithm(op.name, d0.algorithm)
        except ValueError:
            return int(ErrorCode.INVALID_CALL)
        if d0.algorithm in (CollectiveAlgorithm.RING,
                            CollectiveAlgorithm.FUSED_RING):
            alg = "ring"
        elif d0.algorithm != CollectiveAlgorithm.AUTO:
            alg = "xla"
        # rooted ops default to the hierarchical 2D-mesh tree when the comm
        # has 2D structure — O(outer+inner) hop fan-out instead of the
        # psum/all_gather-class traffic of the masked 1-D lowerings (which
        # cost allreduce/allgather bandwidth regardless of root). Explicit
        # ROUND_ROBIN/RING selectors keep the 1-D path; the TREE selector
        # exists only for bcast (VALID_ALGORITHMS — scatter/gather/reduce
        # reach the tree via AUTO). Rooted reduce rides the tree only
        # uncompressed: the tree has no wire-compression lanes, and the
        # compressed 1-D path's decompress-before-arith numerics must win.
        rooted = (CCLOp.bcast, CCLOp.scatter, CCLOp.gather, CCLOp.reduce)
        use_tree = (op in rooted
                    and (d0.algorithm == CollectiveAlgorithm.AUTO
                         or (op == CCLOp.bcast
                             and d0.algorithm == CollectiveAlgorithm.TREE))
                    and not (op == CCLOp.reduce and wire is not None))
        tree = ctx.tree_for(comm) if use_tree else None
        root = d0.root_src_dst
        if op == CCLOp.barrier:
            return 0  # rendezvous above IS the barrier

        def wire_q(arr: np.ndarray) -> np.ndarray:
            """Wire-compression semantics for rooted data movement: a
            payload that crossed the wire was quantized through the
            compressed dtype (emulator-tier parity — without this the
            TPU tier would silently return MORE accurate results than
            the other tiers for ETH-compressed bcast/scatter/gather)."""
            if wire is None:
                return arr
            return arr.astype(wire).astype(cfg.uncompressed_dtype)

        def wire_q_except(flat: np.ndarray, keep: int) -> np.ndarray:
            """Quantize a (W*count,) assembly of per-rank chunks through
            the wire, restoring chunk ``keep`` (the data that stayed
            local: the root's own chunk / a rank's self chunk)."""
            if wire is None:
                return flat
            rows = wire_q(flat.reshape(W, -1))
            rows[keep] = flat.reshape(W, -1)[keep]
            return rows.reshape(-1)
        if op == CCLOp.allreduce:
            x = coll.shard(read_all(lambda d: d.addr_0, count))
            out = np.asarray(coll.allreduce(x, func=d0.function,
                                            algorithm=alg, wire_dtype=wire))
            for r, d in enumerate(descs):
                devs[r]._write_result(d.addr_2, out[r], d)
            return 0
        if op == CCLOp.reduce:
            rows = read_all(lambda d: d.addr_0, count)
            if tree is not None:
                out = np.asarray(tree.reduce(tree.shard(rows), root=root,
                                             func=d0.function))
            else:
                out = np.asarray(coll.reduce(coll.shard(rows), root=root,
                                             func=d0.function,
                                             wire_dtype=wire))
            devs[root]._write_result(descs[root].addr_2, out[root],
                                     descs[root])
            return 0
        if op == CCLOp.reduce_scatter:
            x = coll.shard(read_all(lambda d: d.addr_0, W * count))
            out = np.asarray(coll.reduce_scatter(x, func=d0.function,
                                                 algorithm=alg,
                                                 wire_dtype=wire))
            for r, d in enumerate(descs):
                devs[r]._write_result(d.addr_2, out[r][:count], d)
            return 0
        if op == CCLOp.allgather:
            x = coll.shard(read_all(lambda d: d.addr_0, count))
            out = np.asarray(coll.allgather(x, algorithm=alg,
                                            wire_dtype=wire))
            for r, d in enumerate(descs):
                devs[r]._write_result(d.addr_2, out[r], d)
            return 0
        if op == CCLOp.bcast:
            rows = read_all(lambda d: d.addr_0, count)
            if tree is not None:
                out = np.asarray(tree.bcast(tree.shard(rows), root=root))
            else:
                out = np.asarray(coll.bcast(coll.shard(rows), root=root))
            for r, d in enumerate(descs):
                if r != root:  # root's own buffer never crossed the wire
                    devs[r]._write_result(d.addr_0, wire_q(out[r]), d)
            return 0
        if op == CCLOp.scatter:
            rows = read_all(lambda d: d.addr_0, W * count)
            if tree is not None:
                out = np.asarray(tree.scatter(tree.shard(rows), root=root))
            else:
                out = np.asarray(coll.scatter(coll.shard(rows), root=root))
            for r, d in enumerate(descs):
                chunk = out[r][:count]
                devs[r]._write_result(
                    d.addr_2, chunk if r == root else wire_q(chunk), d)
            return 0
        if op == CCLOp.gather:
            rows = read_all(lambda d: d.addr_0, count)
            if tree is not None:
                out = np.asarray(tree.gather(tree.shard(rows), root=root))
            else:
                out = np.asarray(coll.gather(coll.shard(rows), root=root))
            devs[root]._write_result(descs[root].addr_2,
                                     wire_q_except(out[root], root),
                                     descs[root])
            return 0
        if op == CCLOp.alltoall:
            x = coll.shard(read_all(lambda d: d.addr_0, W * count))
            out = np.asarray(coll.alltoall(x))
            for r, d in enumerate(descs):
                # chunk s->r crossed the wire for every s except r's own
                # local copy (emulator-tier parity, like the rooted ops)
                devs[r]._write_result(d.addr_2, wire_q_except(out[r], r), d)
            return 0
        return int(ErrorCode.COLLECTIVE_NOT_IMPLEMENTED)


def tpu_world(world_size: int | None = None, platform: str | None = None,
              algorithm: str = "xla", timeout: float = DEFAULT_TIMEOUT_S
              ) -> list:
    """Create ACCL instances backed by a device mesh (one rank per device).

    The TPU-tier analog of testing.emu_world."""
    from ..accl import ACCL
    from ..communicator import Communicator, Rank
    ctx = TpuContext(world_size, platform=platform, algorithm=algorithm)
    W = ctx.world_size
    accls = []
    for r in range(W):
        comm = Communicator(ranks=[Rank() for _ in range(W)], local_rank=r)
        accls.append(ACCL(ctx.device(r), comm, timeout=timeout))
    return accls
