"""TPU backend: the ACCL call surface executed on a jax device mesh.

Architecture (the survey's "hard part (a)" — two-sided semantics on an SPMD
substrate): one process is the SPMD controller of all ranks (standard JAX).
Each rank still gets its own ``TpuDevice`` view + ``ACCL`` driver instance,
so the same rank-parallel test corpus drives every tier. Cross-rank
coordination happens in a host-side rendezvous:

* **Collectives** rendezvous all member ranks' calls (matched in per-rank
  program order, MPI semantics); the last arriving rank executes ONE
  shard_map program over the mesh (MeshCollectives) and scatters results
  into every rank's buffer.
* **send** is eager: the payload is snapshotted onto the sender's device
  and the call completes (reference parity: eager ingress lets send
  finish before recv posts). **recv** matches pending sends by
  ``(comm, src, dst, tag)`` + sequence order — that host rendezvous is
  control plane only; the DATA then crosses the device fabric via one
  ppermute program (``TpuContext.exchange_transfer``), riding ICI on a
  real mesh exactly like the reference's send/recv ride its transport
  (ccl_offload_control.c:339-380).

Buffer staging has two modes:

* **Host-mirror buffers** (the default) stage through host numpy per
  call — API parity with the emulator corpus, ~5x per-call overhead
  (``benchmarks/driver_overhead.py``).
* **Device-resident buffers** (``ACCL.buffer(data=<jax.Array>)`` or
  ``device_resident=True`` — the reference's ``to_from_fpga=False``)
  skip host staging entirely: dense collectives assemble the per-rank
  arrays into the flat global with
  ``jax.make_array_from_single_device_arrays``, run one cached program,
  and rebind each rank's dst to its result shard; send snapshots are
  zero-copy (jax.Arrays are immutable). This closes most of the tier
  gap; ``MeshCollectives`` inside your own pjit/shard_map program
  remains the absolute-peak path bench.py measures.
"""

from __future__ import annotations

import collections
import math
import queue
import threading
import time
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..buffer import ACCLBuffer
from ..call import CallDescriptor, CallHandle
from ..communicator import Communicator
from ..constants import (ACCLError, CCLOp, CollectiveAlgorithm, Compression,
                         StreamFlags,
                         DEFAULT_MAX_SEGMENT_SIZE, DEFAULT_TIMEOUT_S,
                         ErrorCode, ReduceFunc, check_algorithm)
from ..emulator.executor import DeviceMemory
from ..log import get_logger
from ..parallel.collectives import MeshCollectives, _wire_name
from ..parallel.mesh import make_mesh
from ..parallel.tree import Tree2DCollectives
from ..rma.window import WindowRegistry
from .base import Device

log = get_logger(__name__)


def _noncanonical(dtype) -> bool:
    """True for dtypes jax cannot represent with x64 off (int64/f64 →
    canonicalized to 32 bits). Payloads of these dtypes must NEVER touch
    jax.device_put or a jnp cast — both silently truncate — so every
    datapath gates on this ONE predicate: stream-port staging, the
    streamed-local ops, and the cross-rank send refusal."""
    d = np.dtype(dtype)
    return jax.dtypes.canonicalize_dtype(d) != d


def _factor_2d(w: int) -> tuple[int, int]:
    """Largest divisor pair (outer, inner) with outer <= inner — the 2D
    mesh shape the tree collectives ride. (1, w) means no 2D structure."""
    o = int(w ** 0.5)
    while o > 1 and w % o:
        o -= 1
    return o, w // o

_COLLECTIVES = {CCLOp.bcast, CCLOp.scatter, CCLOp.gather, CCLOp.reduce,
                CCLOp.allgather, CCLOp.allreduce, CCLOp.reduce_scatter,
                CCLOp.alltoall, CCLOp.barrier}

# on-device combine arithmetic for the streamed/fused local datapath
_COMBINE_JNP = {ReduceFunc.SUM: jnp.add, ReduceFunc.MAX: jnp.maximum,
                ReduceFunc.MIN: jnp.minimum, ReduceFunc.PROD: jnp.multiply}


def _window_land(dst, payload, off):
    flat = jax.lax.dynamic_update_slice(dst.reshape(-1), payload, (off,))
    return flat.reshape(dst.shape)


# RMA put landing: one donated program updates the window buffer in place
# (XLA reuses the donated allocation), so a put into a device-resident
# window never materializes a second full-size copy, let alone a host
# round-trip. `off` is a traced element offset — one compile per window
# geometry, not per offset.
_window_put_prog = jax.jit(_window_land, donate_argnums=(0,))


class _XchgEntry:
    """One matched p2p transfer waiting in the exchange window."""

    __slots__ = ("src", "dst", "payload", "result", "error", "done")

    def __init__(self, src: int, dst: int, payload):
        self.src = src
        self.dst = dst
        self.payload = payload
        self.result = None
        self.error: BaseException | None = None
        self.done = threading.Event()


class TpuContext:
    """Shared state of an N-rank TPU-backed world (single SPMD controller)."""

    def __init__(self, world_size: int | None = None, mesh=None,
                 axis_name: str = "rank", platform: str | None = None,
                 algorithm: str = "xla"):
        if mesh is None:
            mesh = make_mesh((world_size,) if world_size else None,
                             (axis_name,), platform=platform)
        self.mesh = mesh
        self.axis_name = axis_name
        self.world_size = mesh.shape[axis_name]
        self.coll = MeshCollectives(mesh, axis_name)
        self._subcolls: dict[int, MeshCollectives] = {}
        self._subtrees: dict[int, Tree2DCollectives | None] = {}
        self.tree = self._make_tree(
            list(np.asarray(mesh.devices).reshape(-1)))
        self.algorithm = algorithm
        self.devices: list[TpuDevice | None] = [None] * self.world_size
        # rendezvous state
        self._lock = threading.Condition()
        # (comm_id, op_index) -> {comm-local rank: (desc, handle, deadline)}
        self._pending: dict[tuple, dict] = {}
        self._sweeper: threading.Thread | None = None
        # (comm_id, src_g, dst_g) -> deque of (tag, payload jax.Array).
        # Payloads now live in device memory (eager-send snapshots), so
        # unmatched sends pin scarce HBM: like the emulator's finite
        # spare-buffer pool, the parked-send count is bounded and an
        # overflowing send fails with the pool-overflow error instead of
        # leaking (emulator/executor.py RxBufferPool parity).
        self._sends: dict[tuple, collections.deque] = \
            collections.defaultdict(collections.deque)
        self.max_parked_sends = 1024  # across the context, like nbufs
        self._parked_sends = 0        # running count (guarded by _lock)
        # filler shards for the exchange program: ranks that are neither
        # src nor dst of a transfer still contribute an operand shard.
        # Cached per (device, size, dtype) — they're constant zeros.
        self._zeros: dict[tuple, jax.Array] = {}
        self._zeros_mu = threading.Lock()
        # exchange window: comm_id -> queued _XchgEntry; comm_ids with a
        # live batch executor (guarded by _lock)
        self._xchg_pending: dict[int, list] = collections.defaultdict(list)
        self._xchg_running: set[int] = set()

    # cap on cached filler shards: a size sweep would otherwise pin one
    # device array per distinct (device, size, dtype) forever
    _MAX_ZERO_CACHE = 64

    def zero_shard(self, dev, n: int, dtype) -> jax.Array:
        key = (dev, n, np.dtype(dtype).name)
        # fast path without the lock: dict reads are atomic, and a stale
        # miss only costs a redundant zeros build below
        arr = self._zeros.get(key)
        if arr is None:
            arr = jax.device_put(np.zeros(n, dtype), dev)
            with self._zeros_mu:  # eviction+insert race-free (concurrent
                if len(self._zeros) >= self._MAX_ZERO_CACHE:  # recv threads)
                    # FIFO eviction (dict preserves insertion order): drop
                    # the oldest size class rather than growing device
                    # memory
                    self._zeros.pop(next(iter(self._zeros)), None)
                arr = self._zeros.setdefault(key, arr)
        return arr

    def assemble_flat(self, coll: MeshCollectives,
                      shards: list) -> jax.Array:
        """Build the flat global (W*n,) array from per-rank 1-D device
        arrays without host staging: each shard must already live on (or
        is moved to) its comm-local rank's device."""
        devs = coll.device_list
        n = shards[0].shape[0]
        placed = []
        for dev, arr in zip(devs, shards):
            # arr.device is a cheap C property on single-device arrays;
            # devices() builds a frozenset per call (~10us each)
            if getattr(arr, "device", None) != dev:
                arr = jax.device_put(arr, dev)
            placed.append(arr)
        return jax.make_array_from_single_device_arrays(
            (len(devs) * n,), coll.flat_sharding, placed)

    def exchange_transfer(self, comm: Communicator, payload: jax.Array,
                          src_local: int, dst_local: int) -> jax.Array:
        """Move one matched send/recv payload across the device fabric:
        a ppermute program over the communicator's mesh (parity: the
        reference's send/recv ride the real transport end-to-end,
        ccl_offload_control.c:339-380). Returns the received shard (on
        the destination rank's device).

        Matched pairs BATCH opportunistically: transfers deposited while
        an exchange program is running ride the next program together
        (one ppermute with per-pair payloads) instead of one full-mesh
        program each — K concurrent sendrecvs execute in <=2 programs,
        not K, with no added latency for a solo transfer (the first
        arrival never waits for a window to fill)."""
        entry = _XchgEntry(src_local, dst_local, payload)
        cid = comm.comm_id
        with self._lock:
            self._xchg_pending[cid].append(entry)
        # Cooperative leadership, ONE batch per claim: any thread whose
        # entry is pending may claim the free executor flag, run exactly
        # the window present at claim time, then hand off — so a leader
        # is never captured by other ranks' sustained traffic (bounded
        # extra work: one batch), while transfers deposited during a
        # running program still pile into the next claim together.
        while True:
            with self._lock:
                if entry.done.is_set():
                    break
                claimed = (cid not in self._xchg_running
                           and bool(self._xchg_pending[cid]))
                if claimed:
                    self._xchg_running.add(cid)
                    batch = self._xchg_pending[cid]
                    self._xchg_pending[cid] = []
            if not claimed:
                # Wait on the shared Condition: the leader notifies it
                # after every completed round AND on batch handoff, so a
                # waiter wakes immediately both when its own transfer
                # completes mid-batch and when leadership frees up —
                # sleeping on the per-entry Event instead would miss the
                # handoff notify and eat a full poll tick. The short
                # timeout stays as the backstop if a leader died.
                with self._lock:
                    if not entry.done.is_set() and cid in self._xchg_running:
                        self._lock.wait(0.05)
                continue
            try:
                self._run_exchange_batch(comm, batch)
            except BaseException as exc:
                for e in batch:
                    if not e.done.is_set():  # completed rounds stand
                        e.error = exc
                        e.done.set()
            finally:
                with self._lock:
                    self._xchg_running.discard(cid)
                    self._lock.notify_all()
        if entry.error is not None:
            raise entry.error
        return entry.result

    def _run_exchange_batch(self, comm: Communicator, entries: list):
        """Execute one window of matched transfers: entries group by
        payload geometry, each group splits greedily into permutation
        rounds (a ppermute source/destination appears once per round),
        and every round is ONE exchange program."""
        coll = self.coll_for(comm)
        devs = coll.device_list
        groups: dict[tuple, list] = collections.defaultdict(list)
        for e in entries:
            groups[(e.payload.shape[0], str(e.payload.dtype))].append(e)
        for (n, _dt), group in groups.items():
            remaining = group
            while remaining:
                round_entries, nxt = [], []
                srcs, dsts = set(), set()
                for e in remaining:
                    if e.src in srcs or e.dst in dsts:
                        nxt.append(e)   # conflicts ride the next round
                    else:
                        srcs.add(e.src)
                        dsts.add(e.dst)
                        round_entries.append(e)
                remaining = nxt
                by_src = {e.src: e for e in round_entries}
                shards = [by_src[r].payload if r in by_src
                          else self.zero_shard(
                              d, n, round_entries[0].payload.dtype)
                          for r, d in enumerate(devs)]
                x = self.assemble_flat(coll, shards)
                pairs = tuple(sorted((e.src, e.dst)
                                     for e in round_entries))
                out = coll.exchange_flat(x, pairs)
                by_dst = {e.dst: e for e in round_entries}
                for s in out.addressable_shards:
                    r = (s.index[0].start or 0) // n
                    e = by_dst.get(r)
                    if e is not None:
                        e.result = s.data
                        e.done.set()
                for e in round_entries:   # paranoia: no silent waiter
                    if not e.done.is_set():
                        e.error = RuntimeError(
                            "destination shard missing from exchange")
                        e.done.set()
                with self._lock:
                    # wake Condition sleepers whose entries just
                    # completed (they no longer sleep on the Event)
                    self._lock.notify_all()

    def device(self, rank: int) -> "TpuDevice":
        if self.devices[rank] is None:
            self.devices[rank] = TpuDevice(self, rank)
        return self.devices[rank]

    # -- deadline sweeper ---------------------------------------------------
    def _ensure_sweeper(self):
        """Start the (single, lazy) deadline sweeper. Caller holds _lock.

        Members of an incomplete rendezvous group no longer park a thread
        each, so their per-call timeout is enforced centrally: the sweeper
        fails any deposit whose deadline passed with
        RECEIVE_TIMEOUT_ERROR and removes its slot — a group missing a
        member can then never complete, and its remaining deposits expire
        on their own deadlines (the old per-waiter semantics)."""
        if self._sweeper is None:
            self._sweeper = threading.Thread(target=self._sweep_loop,
                                             daemon=True,
                                             name="tpu-coll-sweeper")
            self._sweeper.start()

    def _sweep_loop(self):
        from ..constants import ACCLError
        idle_scans = 0
        while True:
            with self._lock:
                now = time.monotonic()
                expired = []
                next_dl = None
                for key, group in list(self._pending.items()):
                    for r, (d, h, dl) in list(group.items()):
                        if dl <= now:
                            group.pop(r)
                            expired.append(h)
                        elif next_dl is None or dl < next_dl:
                            next_dl = dl
                    if not group:
                        self._pending.pop(key, None)
                if not self._pending and not expired:
                    idle_scans += 1
                    if idle_scans >= 10:
                        # nothing pending for ~2s: retire rather than
                        # polling forever (long-lived processes creating
                        # many worlds would accumulate pollers); the next
                        # incomplete deposit restarts it
                        self._sweeper = None
                        return
                else:
                    idle_scans = 0
            for h in expired:
                if h is not None:
                    err = int(ErrorCode.RECEIVE_TIMEOUT_ERROR)
                    h.complete(err, exception=ACCLError(
                        err, "collective group incomplete at deadline"))
            # Deposits never wake the sweeper (a wakeup per member per
            # collective is pure GIL churn on the hot path, and waiting
            # on ctx._lock would make every send's notify_all a spurious
            # wake). It polls: 200 ms cadence when idle, the exact
            # earliest deadline when groups are pending — a timeout may
            # fire up to one poll late, which RECEIVE_TIMEOUT semantics
            # tolerate.
            now = time.monotonic()
            time.sleep(0.2 if next_dl is None
                       else min(max(next_dl - now, 0.001), 0.2))

    @staticmethod
    def _make_tree(devs) -> Tree2DCollectives | None:
        """Hierarchical collectives over the same devices folded into the
        largest 2D factorization — the bandwidth-correct path for rooted
        ops at scale (BASELINE config 4's 32-rank (8,4) trees). None when
        the world has no 2D structure (prime or < 4 ranks)."""
        from jax.sharding import Mesh
        o, i = _factor_2d(len(devs))
        if o < 2:
            return None
        return Tree2DCollectives(
            Mesh(np.asarray(devs).reshape(o, i), ("outer", "inner")))

    def _comm_devices(self, comm: Communicator) -> list:
        """The communicator's devices in comm-local rank order (one
        rank->device convention for every sub-mesh built from the world)."""
        world_devs = list(np.asarray(self.mesh.devices).reshape(-1))
        return [world_devs[r.global_rank] for r in comm.ranks]

    def coll_for(self, comm: Communicator) -> MeshCollectives:
        """Collectives bound to the communicator's sub-mesh: member global
        ranks select their devices from the world mesh (a split comm runs
        over its own axis, so axis_index == comm-local rank). Cache fills
        take the ctx lock — launchers of disjoint comms run concurrently."""
        if comm.size == self.world_size:
            return self.coll
        key = comm.comm_id
        with self._lock:
            cached = self._subcolls.get(key)
        if cached is not None:
            return cached
        from jax.sharding import Mesh
        sub = MeshCollectives(
            Mesh(np.asarray(self._comm_devices(comm)), (self.axis_name,)),
            self.axis_name)
        with self._lock:
            return self._subcolls.setdefault(key, sub)

    def tree_for(self, comm: Communicator) -> Tree2DCollectives | None:
        """The communicator's 2D tree context (None when its size has no
        2D factorization)."""
        if comm.size == self.world_size:
            return self.tree
        key = comm.comm_id
        with self._lock:
            if key in self._subtrees:
                return self._subtrees[key]
        tree = self._make_tree(self._comm_devices(comm))
        with self._lock:
            return self._subtrees.setdefault(key, tree)


class DeviceStreamPort:
    """Device-resident external-kernel stream ports for one rank.

    The TPU-native mapping of the reference's AXIS stream ports
    (SWITCH_M_BYPASS, streamdefines.h:39): entries are 1-D jax arrays
    living on this rank's device — a staging ring the fused ops read
    from and write to WITHOUT the payload ever visiting the host.
    Continuous-stream semantics mirror the emulator executor's ports:
    a take may span entries and consume one partially; a shortfall
    blocks to a deadline and consumes nothing on timeout (stalled-AXIS
    parity, KRNL_TIMEOUT upstream)."""

    def __init__(self, device):
        self.dev = device                     # the rank's jax device
        self._in: collections.deque = collections.deque()
        self._in_off = 0                      # consumed prefix of _in[0]
        self._out: collections.deque = collections.deque()
        self._out_off = 0
        self._cv = threading.Condition()

    def push(self, data) -> None:
        # own the bytes: device_put ALIASES host memory on some backends
        # (cpu), and the host-preserved branch would otherwise keep a
        # view — either way a caller mutating its array after push would
        # corrupt the staged entry (same eager-snapshot contract as
        # _do_send)
        host = np.array(data, copy=True).reshape(-1)
        if not _noncanonical(host.dtype):
            entry = jax.device_put(host, self.dev)  # one transfer
        else:
            # dtype jax cannot represent with x64 off (int64/f64): keep
            # the host array — truncating user bits on a stream port is
            # never acceptable (the emulator tiers preserve them)
            entry = host
        with self._cv:
            self._in.append(entry)
            self._cv.notify_all()

    @staticmethod
    def _avail(q, off) -> int:
        return sum(e.shape[0] for e in q) - off

    @staticmethod
    def _assemble(q, off, count, dtype):
        """Pop ``count`` elements off the front of ``q`` (device slices,
        concatenated on device; host-preserved 64-bit entries assemble
        on host so their bits survive). Returns (array, new_off)."""
        pieces = []
        need = count
        while need:
            e = q[0]
            take = min(need, e.shape[0] - off)
            piece = e if (off == 0 and take == e.shape[0]) \
                else e[off:off + take]
            pieces.append(piece)
            need -= take
            off += take
            if off == e.shape[0]:
                q.popleft()
                off = 0
        if any(isinstance(p, np.ndarray) for p in pieces):
            out = (pieces[0] if len(pieces) == 1
                   else np.concatenate([np.asarray(p) for p in pieces]))
            if dtype is not None and out.dtype != np.dtype(dtype):
                out = out.astype(dtype)
            return out, off
        out = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
        if dtype is not None and out.dtype != jnp.dtype(dtype):
            out = out.astype(dtype)
        return out, off

    def take(self, count: int, dtype, deadline: float):
        """Blocking stream-in read of exactly ``count`` elements; None on
        timeout (nothing consumed — a retry after the rest arrives must
        succeed)."""
        with self._cv:
            while self._avail(self._in, self._in_off) < count:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cv.wait(remaining):
                    return None
            out, self._in_off = self._assemble(self._in, self._in_off,
                                               count, dtype)
            return out

    def put_out(self, arr) -> None:
        with self._cv:
            self._out.append(arr.reshape(-1))
            self._cv.notify_all()

    def put_in(self, arr) -> None:
        """Remote-stream delivery (a peer's stream_put lands here)."""
        with self._cv:
            self._in.append(arr.reshape(-1))
            self._cv.notify_all()

    def pop(self, timeout: float = 0.0, count: int | None = None):
        """Stream-out read: ``count`` elements across entries, or the
        next entry whole (count None/0). IndexError when it never fills
        (emulator pop_stream_out parity)."""
        deadline = time.monotonic() + timeout
        if not count:
            count = None
        with self._cv:
            while True:
                if count is None:
                    if self._out:
                        e = self._out[0]
                        if self._out_off:
                            e, _ = self._assemble(
                                self._out, self._out_off,
                                e.shape[0] - self._out_off, None)
                            self._out_off = 0
                        else:
                            self._out.popleft()
                        return e
                elif self._avail(self._out, self._out_off) >= count:
                    out, self._out_off = self._assemble(
                        self._out, self._out_off, count, None)
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cv.wait(remaining):
                    raise IndexError("stream-out port empty")

    def reset(self) -> None:
        with self._cv:
            self._in.clear()
            self._out.clear()
            self._in_off = self._out_off = 0


class TpuDevice(Device):
    """One rank's view of the TPU-backed world."""

    def __init__(self, ctx: TpuContext, rank: int):
        self.ctx = ctx
        self.rank = rank
        self.mem = DeviceMemory()          # host mirrors of device buffers
        # device-resident buffers (no host mirror): address -> ACCLBuffer
        # whose .jax is the live array on this rank's device
        self.dev_bufs: dict[int, ACCLBuffer] = {}
        self.my_device = list(
            np.asarray(ctx.mesh.devices).reshape(-1))[rank]
        self.windows = WindowRegistry()    # one-sided RMA address space
        self.comms: dict[int, Communicator] = {}
        self.comm: Communicator | None = None
        self.timeout = DEFAULT_TIMEOUT_S
        self.max_segment_size = DEFAULT_MAX_SEGMENT_SIZE
        self.profiling = False  # armed by the start_profiling config call
        self.sport = DeviceStreamPort(self.my_device)
        self._coll_index: dict[int, int] = collections.defaultdict(int)
        self._calls: queue.Queue = queue.Queue()
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=f"tpu-rank{rank}")
        self._worker.start()

    # -- Device interface --------------------------------------------------
    def register_buffer(self, buf: ACCLBuffer):
        if buf.is_device_resident:
            self.dev_bufs[buf.address] = buf
        else:
            self.mem.register(buf.address, buf.data)

    def deregister_buffer(self, buf: ACCLBuffer):
        if buf.is_device_resident:
            self.dev_bufs.pop(buf.address, None)
        else:
            self.mem.deregister(buf.address)

    # -- one-sided RMA windows (accl_tpu/rma) ------------------------------
    def register_window(self, wid: int, addr: int, nbytes: int):
        self.windows.register(wid, addr, nbytes)

    def deregister_window(self, wid: int):
        self.windows.deregister(wid)

    # -- device-resident storage (the to_from_fpga=False fast path) --------
    def adopt_device_array(self, arr):
        """Home a live jax.Array on this rank's mesh device. Committed
        single-device arrays already there are adopted zero-copy."""
        devs = arr.devices()
        if len(devs) != 1:
            raise ValueError(
                "device-resident ACCL buffers wrap single-device arrays "
                "(one rank, one device); got a sharded array — pass it "
                "to MeshCollectives / your shard_map program directly")
        if list(devs)[0] != self.my_device:
            arr = jax.device_put(arr, self.my_device)
        return arr

    def make_device_array(self, shape, dtype, init=None):
        if _noncanonical(dtype):
            # with x64 off, device_put would quietly canonicalize the
            # array to 32 bits AT CREATION — every later read of the
            # "int64/f64 device buffer" would see truncated values.
            # Refuse here, the root of that datapath, rather than let
            # _write_result discover the corruption later.
            raise ValueError(
                f"device-resident buffers cannot hold {np.dtype(dtype).name}"
                f" (jax x64-off canonicalizes it to 32 bits); use a "
                f"host-mirror buffer for 64-bit dtypes")
        host = (np.zeros(shape, dtype) if init is None
                else np.asarray(init, dtype).reshape(shape))
        return jax.device_put(host, self.my_device)

    def configure_communicator(self, comm: Communicator,
                               tenant: str | None = None):
        # tenant grouping accepted for interface parity; the TPU tier's
        # per-tenant scheduling lives in the service layer upstream
        self.comms[comm.comm_id] = comm
        if self.comm is None:
            self.comm = comm

    def set_timeout(self, timeout: float):
        self.timeout = timeout

    def set_max_segment_size(self, nbytes: int):
        self.max_segment_size = nbytes

    def topology(self):
        """Mesh tier: an ICI hop is ~a microsecond and per-link bandwidth
        is in the 100 GB/s class on real chips; on the CPU-mesh stand-in
        the same ordering holds (host collectives, negligible per-hop
        software cost vs the emulator tiers)."""
        from ..tuner.cost import Topology
        return Topology(world_size=self.ctx.world_size, alpha_us=1.0,
                        beta_gbps=100.0, tier="tpu")

    def auto_resolvable_ops(self):
        """The rooted ops (bcast/scatter/gather/reduce) keep their AUTO:
        on 2D meshes it lowers to the hierarchical tree (O(outer+inner)
        fan-out), and a tuner resolving AUTO to ROUND_ROBIN/RING would
        force the masked 1-D lowering — allreduce/allgather-class
        traffic regardless of root — based on cost models shaped for the
        move-engine tiers. (bcast does have a TREE selector, but the
        tuner's small-message choice would be ROUND_ROBIN, the exact
        degradation; callers who want the 1-D path can force it.) The
        dense collectives map cleanly onto the xla/ring axis the tuner
        chooses between."""
        return frozenset({"allreduce", "allgather", "reduce_scatter"})

    # Inline eligibility in the submitting thread, preserving the async
    # contract (call_async must not block an async caller on real work):
    # - nop/config are trivial — always inline.
    # - collectives always inline their DEPOSIT (non-blocking, ~10us);
    #   when the deposit completes the group, the heavy launch runs
    #   inline only for synchronous callers (inline_ok — they'd block in
    #   wait() anyway) and hops to the worker for async ones.
    # - send/recv/copy/combine do real work (staging, or blocking on a
    #   peer for recv) — inline only when the caller declared it will
    #   immediately wait (inline_ok).
    _TRIVIAL_OPS = {CCLOp.nop, CCLOp.config}
    _SYNC_INLINE_OPS = {CCLOp.send, CCLOp.recv, CCLOp.copy, CCLOp.combine}

    def call_async(self, desc: CallDescriptor,
                   waitfor: Sequence[CallHandle] = (), *,
                   inline_ok: bool = False) -> CallHandle:
        handle = CallHandle(context=desc.scenario.name)
        op = desc.scenario
        # Inline fast path: skip the worker-thread hop (queue + wakeup +
        # GIL handoff per call — the dominant per-call cost of this tier)
        # whenever per-rank FIFO order is provable: nothing queued or
        # running on the worker (the shared inline gate) and every
        # dependency already retired.
        if (op in self._TRIVIAL_OPS or op in _COLLECTIVES
                or (op in self._SYNC_INLINE_OPS and inline_ok)) \
                and self._inline_begin(waitfor):
            try:
                self._run_one(desc, waitfor, handle,
                              defer_launch=(op in _COLLECTIVES
                                            and not inline_ok))
            finally:
                self._inflight_done()
            return handle
        self._inflight_add()
        self._calls.put((desc, tuple(waitfor), handle))
        return handle

    def soft_reset(self):
        with self.ctx._lock:
            self.ctx._sends.clear()
            self.ctx._parked_sends = 0
        self._coll_index.clear()
        # stale cross-epoch stream data must not leak to the next
        # consumer (emulator reset_streams parity)
        self.sport.reset()

    def deinit(self):
        self._calls.put(None)

    # -- worker ------------------------------------------------------------
    def _run(self):
        while True:
            item = self._calls.get()
            if item is None:
                return
            try:
                if callable(item):
                    item()  # deferred group launch (async last arrival)
                else:
                    desc, waitfor, handle = item
                    self._run_one(desc, waitfor, handle)
            finally:
                self._inflight_done()

    def _run_one(self, desc: CallDescriptor, waitfor, handle: CallHandle,
                 defer_launch: bool = False):
        """Retire one call in the current thread. Completes ``handle``
        unless the call parked in a rendezvous group (collective deposit:
        the group-completing rank — or the deadline sweeper — completes
        it). ``defer_launch`` hops a group-completing launch to the
        worker thread instead of running it here (async submissions must
        not block in call_async)."""
        from ..constants import ACCLError
        try:
            if (desc.deadline is not None
                    and time.monotonic() >= desc.deadline):
                # queued past the caller's bound: the caller's wait already
                # raised, so executing now would mutate buffers it has
                # moved on from — fail instead of running late
                handle.complete(int(ErrorCode.RECEIVE_TIMEOUT_ERROR))
                return
            for dep in waitfor:
                dep.wait(self.timeout if desc.deadline is None
                         else max(0.0, desc.deadline - time.monotonic()))
            err = self._execute(desc, handle, defer_launch)
            if err is not None:
                handle.complete(err)
        except ACCLError as exc:
            handle.complete(exc.error_word, exception=exc)
        except TimeoutError as exc:
            handle.complete(int(ErrorCode.RECEIVE_TIMEOUT_ERROR),
                            exception=exc)
        except Exception as exc:  # noqa: BLE001
            handle.complete(int(ErrorCode.INVALID_CALL), exception=exc)

    # -- operand staging ---------------------------------------------------
    def _read_operand(self, addr: int, count: int, desc, which: Compression
                      ) -> np.ndarray:
        cfg = desc.arithcfg
        buf = self.dev_bufs.get(addr)
        if buf is not None:
            # device-resident source on a host-staged path: one D2H read.
            # The stored dtype IS the array's dtype (no separate
            # compressed mirror exists for device buffers).
            arr = np.asarray(buf.jax).reshape(-1)
            if count > arr.size:
                from ..constants import ACCLError
                raise ACCLError(int(ErrorCode.DMA_SIZE_ERROR),
                                f"read past device buffer end "
                                f"({count} > {arr.size})")
            return arr[:count].astype(cfg.uncompressed_dtype, copy=False)
        stored = (cfg.compressed_dtype if desc.compression & which
                  else cfg.uncompressed_dtype)
        return self.mem.read(addr, count, stored).astype(
            cfg.uncompressed_dtype, copy=False)

    def _write_result(self, addr: int, data: np.ndarray, desc):
        cfg = desc.arithcfg
        out = (cfg.compressed_dtype
               if desc.compression & Compression.RES_COMPRESSED
               else cfg.uncompressed_dtype)
        buf = self.dev_bufs.get(addr)
        if buf is not None:
            if _noncanonical(np.dtype(out)):
                # a device-resident landing re-enters _rebind_dev, whose
                # device_put canonicalizes int64/f64 to 32 bits — the
                # silent-truncation path every other noncanon gate in
                # this file exists to prevent. make_device_array rejects
                # creating such buffers, so this guards adopted/aliased
                # corners: refuse loudly rather than corrupt the result.
                from ..constants import ACCLError
                raise ACCLError(
                    int(ErrorCode.INVALID_CALL),
                    f"{np.dtype(out).name} result cannot land in a "
                    f"device-resident buffer (jax x64-off would truncate "
                    f"it); use a host-mirror buffer for 64-bit dtypes")
            self._rebind_dev(buf, np.asarray(data, dtype=out))
            return
        self.mem.write(addr, np.asarray(data, dtype=out))

    def _rebind_dev(self, buf: ACCLBuffer, data):
        """Land a result in a device-resident buffer. jax.Arrays are
        immutable, so a full-size result replaces the array; a partial
        result (segmented host paths) does read-modify-write."""
        n = math.prod(np.shape(data))
        if n == buf.size:
            arr = data if isinstance(data, jax.Array) else \
                jax.device_put(np.asarray(data), self.my_device)
            if arr.dtype != buf.dtype:
                arr = arr.astype(buf.dtype)
            if arr.shape != buf.shape:
                arr = arr.reshape(buf.shape)
            buf._rebind(arr)
            return
        host = np.asarray(buf.jax).reshape(-1).copy()
        host[:n] = np.asarray(data, dtype=buf.dtype).reshape(-1)
        buf._rebind(jax.device_put(host.reshape(buf.shape),
                                   self.my_device))

    # -- execution ---------------------------------------------------------
    def _execute(self, desc: CallDescriptor, handle: CallHandle,
                 defer_launch: bool = False) -> int | None:
        """Returns the call's error word, or None when the call parked in
        a rendezvous group and ``handle`` will be completed elsewhere."""
        op = desc.scenario
        if op == CCLOp.nop:
            return 0
        if op == CCLOp.config:
            return self.apply_config(desc)  # shared dispatch (Device base)
        if desc.stream_flags and op not in (CCLOp.copy, CCLOp.combine,
                                            CCLOp.send, CCLOp.recv):
            # streamed operands on the p2p/local ops ride the device-
            # resident ports (DeviceStreamPort); for collectives a
            # streamed operand belongs INSIDE the jitted program — reject
            # explicitly rather than silently executing a memory-only
            # variant (the emulator tiers silently ignore the flags
            # there, which is the one behavior we refuse to copy)
            return int(ErrorCode.STREAM_NOT_SUPPORTED)
        comm = self.comms.get(desc.comm_id)
        if comm is None:
            return int(ErrorCode.COMM_NOT_CONFIGURED)
        s_op0 = bool(desc.stream_flags & StreamFlags.OP0_STREAM)
        s_res = bool(desc.stream_flags & StreamFlags.RES_STREAM)
        if op == CCLOp.copy:
            if s_op0 or s_res:
                return self._streamed_local(desc, s_op0, s_res, None)
            data = self._read_operand(desc.addr_0, desc.count, desc,
                                      Compression.OP0_COMPRESSED)
            self._write_result(desc.addr_2, data, desc)
            return 0
        if op == CCLOp.combine:
            if s_op0 or s_res:
                return self._streamed_local(desc, s_op0, s_res,
                                            desc.function)
            from ..emulator.executor import _REDUCERS
            a = self._read_operand(desc.addr_0, desc.count, desc,
                                   Compression.OP0_COMPRESSED)
            b = self._read_operand(desc.addr_1, desc.count, desc,
                                   Compression.OP1_COMPRESSED)
            self._write_result(desc.addr_2, _REDUCERS[desc.function](a, b),
                               desc)
            return 0
        if op == CCLOp.send:
            return self._do_send(desc, comm)
        if op == CCLOp.recv:
            return self._do_recv(desc, comm)
        if op == CCLOp.put:
            return self._do_put(desc, comm)
        if op == CCLOp.get:
            return self._do_get(desc, comm)
        if op in _COLLECTIVES:
            return self._do_collective(desc, comm, handle, defer_launch)
        return int(ErrorCode.COLLECTIVE_NOT_IMPLEMENTED)

    # -- streamed local ops (device-resident port datapath) ----------------
    def _operand_device(self, desc: CallDescriptor, addr: int,
                        which: Compression) -> jax.Array:
        """An operand as a device array: zero-copy for device-resident
        buffers, one H2D for host mirrors."""
        buf = self.dev_bufs.get(addr)
        uncomp = desc.arithcfg.uncompressed_dtype
        if buf is not None and buf.size >= desc.count:
            arr = buf.jax.reshape(-1)[:desc.count]
            return arr.astype(uncomp) if arr.dtype != jnp.dtype(uncomp) \
                else arr
        host = self._read_operand(addr, desc.count, desc, which)
        return jax.device_put(np.array(host, copy=True), self.my_device)

    def _streamed_local(self, desc: CallDescriptor, s_op0: bool,
                        s_res: bool, func) -> int:
        """copy/combine with streamed first operand and/or result: the
        payload stays a device array end to end — port take, (optional)
        on-device arithmetic against op1, port deposit or buffer rebind.
        This is the SURVEY §2.9 mapping of MOVE_STREAM/the bypass port:
        producer and consumer attach at the device-resident ports, and
        the op itself is a fused device program."""
        uncomp = desc.arithcfg.uncompressed_dtype
        # a dtype jax cannot represent with x64 off (int64/f64) must
        # never touch a jnp cast or device_put — both canonicalize to 32
        # bits and silently corrupt the value. The whole datapath stays
        # in numpy for these: port entries host-preserve, arithmetic has
        # a numpy branch, and put_out/_write_result accept host arrays.
        noncanon = _noncanonical(uncomp)
        deadline = (desc.deadline if desc.deadline is not None
                    else time.monotonic() + self.timeout)
        if s_op0:
            data = self.sport.take(desc.count,
                                   None if noncanon else uncomp, deadline)
            if data is None:
                # stalled-stream semantics: same error word as the
                # emulator tiers, nothing consumed
                return int(ErrorCode.KRNL_TIMEOUT_STS_ERROR)
            if noncanon:
                # cast on host from the entries' TRUE dtypes (device
                # entries fetch their exact canonical values; host-
                # preserved entries already carry the full 64 bits)
                data = np.asarray(data).astype(uncomp, copy=False)
        elif noncanon:
            # host read keeps the exact 64-bit operand bits
            data = self._read_operand(desc.addr_0, desc.count, desc,
                                      Compression.OP0_COMPRESSED)
        else:
            data = self._operand_device(desc, desc.addr_0,
                                        Compression.OP0_COMPRESSED)
        if func is not None:
            if isinstance(data, np.ndarray):
                # host-preserved 64-bit entry: arithmetic stays in numpy
                # (jnp would canonicalize both operands to 32 bits and
                # silently corrupt exactly the bits push() preserved)
                from ..emulator.executor import _REDUCERS
                b = self._read_operand(desc.addr_1, desc.count, desc,
                                       Compression.OP1_COMPRESSED)
                data = _REDUCERS[func](data, np.asarray(b, data.dtype))
            else:
                # zero-copy device read for device-resident op1 — the
                # fused datapath must not round-trip it through the host
                b = self._operand_device(desc, desc.addr_1,
                                         Compression.OP1_COMPRESSED)
                data = _COMBINE_JNP[func](data, b)
        if s_res:
            self.sport.put_out(data)
            return 0
        dst = self.dev_bufs.get(desc.addr_2)
        if (dst is not None and dst.size == desc.count and not noncanon
                and not (desc.compression & Compression.RES_COMPRESSED)):
            self._rebind_dev(dst, data)
        else:
            # noncanon results stay on the host write path: _rebind_dev's
            # device_put would canonicalize the 64-bit payload
            self._write_result(desc.addr_2, np.asarray(data), desc)
        return 0

    # -- external-kernel stream ports (Device interface) -------------------
    def push_stream(self, data):
        self.sport.push(data)

    def pop_stream(self, timeout: float = 0.0, count: int | None = None):
        return self.sport.pop(timeout, count)

    # -- send/recv rendezvous ---------------------------------------------
    def _do_send(self, desc: CallDescriptor, comm: Communicator) -> int:
        """Eager send: snapshot the payload onto THIS rank's device and
        park it for the matching recv, which moves it across the fabric
        with a ppermute program (``TpuContext.exchange_transfer``).

        Device-resident sources snapshot zero-copy — jax.Arrays are
        immutable, so holding the reference IS the snapshot (result
        writes rebind, they never mutate). Host-mirror sources pay one
        explicit host copy + H2D, preserving MPI eager semantics (the
        source buffer is reusable the moment send returns)."""
        wire = (desc.arithcfg.compressed_dtype
                if desc.compression & Compression.ETH_COMPRESSED else None)
        if desc.stream_flags & StreamFlags.OP0_STREAM:
            # send-from-stream: the payload comes off the device-resident
            # stream-in port (no buffer, no host staging)
            deadline = (desc.deadline if desc.deadline is not None
                        else time.monotonic() + self.timeout)
            uncomp = np.dtype(desc.arithcfg.uncompressed_dtype)
            if _noncanonical(uncomp):
                # a 64-bit payload cannot cross the device fabric (jax
                # x64 off would truncate it in the exchange program):
                # refuse loudly BEFORE consuming the stream — the
                # emulator tiers carry these, this tier keeps them
                # local-port-only
                return int(ErrorCode.STREAM_NOT_SUPPORTED)
            payload = self.sport.take(desc.count, uncomp, deadline)
            if payload is None:
                return int(ErrorCode.KRNL_TIMEOUT_STS_ERROR)
            if isinstance(payload, np.ndarray):
                # host-preserved entries cast to a canonical dtype by the
                # take land on device here (the gate above guarantees no
                # truncation)
                payload = jax.device_put(payload, self.my_device)
            if wire is not None and payload.dtype != jnp.dtype(wire):
                payload = payload.astype(wire)
        else:
            buf = self.dev_bufs.get(desc.addr_0)
            if (buf is not None and buf.size == desc.count
                    and not (desc.compression & Compression.OP0_COMPRESSED)):
                payload = buf.jax
                if payload.ndim != 1:
                    payload = payload.reshape(-1)
                if wire is not None and payload.dtype != jnp.dtype(wire):
                    payload = payload.astype(wire)  # on-device wire cast
            else:
                host = self._read_operand(desc.addr_0, desc.count, desc,
                                          Compression.OP0_COMPRESSED)
                if wire is not None:
                    host = host.astype(wire)
                # np.array(copy=True): device_put may alias host memory on
                # the CPU backend, and the caller may overwrite the source
                # right after send returns
                payload = jax.device_put(np.array(host, copy=True),
                                         self.my_device)
        if desc.stream_flags & StreamFlags.RES_STREAM:
            # remote-stream send (stream_put): the payload crosses the
            # device fabric and lands on the PEER's stream-in port,
            # bypassing the rx matching queue (strm=1 wire parity,
            # dma_mover.cpp:303) — seqn is NOT consumed
            dst_local = desc.root_src_dst
            peer = self.ctx.devices[
                comm.ranks[dst_local].global_rank]
            if dst_local != comm.local_rank:
                payload = self.ctx.exchange_transfer(
                    comm, payload, comm.local_rank, dst_local)
            if payload.dtype != jnp.dtype(
                    desc.arithcfg.uncompressed_dtype):
                payload = payload.astype(
                    desc.arithcfg.uncompressed_dtype)  # wire decompress
            peer.sport.put_in(payload)
            return 0
        dst_g = comm.ranks[desc.root_src_dst].global_rank
        key = (desc.comm_id, comm.my_global_rank, dst_g)
        ctx = self.ctx
        with ctx._lock:
            if ctx._parked_sends >= ctx.max_parked_sends:
                # eager-buffer exhaustion, not silent HBM retention
                return int(
                    ErrorCode.RECEIVE_OFFCHIP_SPARE_BUFF_OVERFLOW)
            ctx._parked_sends += 1
            ctx._sends[key].append((desc.tag, payload))
            ctx._lock.notify_all()
        return 0

    def _match_send(self, key: tuple, tag: int):
        """Pop the oldest pending send matching ``tag`` (TAG_ANY semantics
        identical to the emulator's RxBufferPool._match). Caller holds the
        ctx lock."""
        from ..constants import TAG_ANY
        pending = self.ctx._sends.get(key)
        if not pending:
            return None
        for i, (stag, payload) in enumerate(pending):
            if tag == TAG_ANY or stag == tag or stag == TAG_ANY:
                del pending[i]
                self.ctx._parked_sends -= 1
                if not pending:
                    del self.ctx._sends[key]
                return payload
        return None

    def _do_recv(self, desc: CallDescriptor, comm: Communicator) -> int:
        src_g = comm.ranks[desc.root_src_dst].global_rank
        me_g = comm.my_global_rank
        key = (desc.comm_id, src_g, me_g)
        deadline = (desc.deadline if desc.deadline is not None
                    else time.monotonic() + self.timeout)
        with self.ctx._lock:
            while True:
                payload = self._match_send(key, desc.tag)
                if payload is not None:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self.ctx._lock.wait(remaining):
                    return int(ErrorCode.RECEIVE_TIMEOUT_ERROR)
        if payload.size != desc.count:
            # emulator-tier parity: envelope length must match the posted
            # receive exactly (DMA_MISMATCH_ERROR, executor._fetch)
            return int(ErrorCode.DMA_MISMATCH_ERROR)
        # The host rendezvous above is control plane only (tag matching,
        # MPI ordering); the DATA crosses the device fabric: one ppermute
        # program over the communicator's mesh moves the snapshot from
        # the sender's device to ours (parity: reference send/recv ride
        # the real transport, ccl_offload_control.c:339-380 + rxbuf
        # ingress). Self-sends skip the program — there is no hop.
        src_local = desc.root_src_dst
        me_local = comm.local_rank
        if src_local == me_local:
            received = payload
        else:
            received = self.ctx.exchange_transfer(comm, payload,
                                                  src_local, me_local)
        uncomp = desc.arithcfg.uncompressed_dtype
        if received.dtype != jnp.dtype(uncomp):
            received = received.astype(uncomp)  # wire decompress, on device
        if desc.stream_flags & StreamFlags.RES_STREAM:
            # recv-to-stream: the received device array lands on the
            # local stream-out port (no buffer, no host staging)
            self.sport.put_out(received)
            return 0
        dst = self.dev_bufs.get(desc.addr_2)
        if (dst is not None and dst.size == desc.count
                and not (desc.compression & Compression.RES_COMPRESSED)):
            self._rebind_dev(dst, received)   # stays on device
        else:
            self._write_result(desc.addr_2, np.asarray(received), desc)
        return 0

    # -- one-sided RMA (put/get against registered windows) ----------------
    def _rma_peer(self, desc: CallDescriptor,
                  comm: Communicator) -> "TpuDevice":
        peer = self.ctx.devices[comm.ranks[desc.root_src_dst].global_rank]
        if peer is None:
            raise ACCLError(int(ErrorCode.COMM_NOT_CONFIGURED),
                            "RMA peer rank has no device configured")
        return peer

    def _do_put(self, desc: CallDescriptor, comm: Communicator) -> int:
        """One-sided write: resolve ``(window, byte offset)`` on the
        TARGET rank — which posts no matching call — move the payload
        across, land it. A device-resident window lands through the
        donated ``_window_put_prog`` (in-place update on the target's
        device, no host staging and no second full-size window copy);
        host-mirror windows and byte-misaligned/mixed-dtype ranges take
        the host read-modify-write path."""
        tgt = self._rma_peer(desc, comm)
        uncomp = np.dtype(desc.arithcfg.uncompressed_dtype)
        nbytes = desc.count * uncomp.itemsize
        base = tgt.windows.resolve(desc.tag, desc.addr_1, nbytes)
        wire = (desc.arithcfg.compressed_dtype
                if desc.compression & Compression.ETH_COMPRESSED else None)
        w = tgt.windows.get(desc.tag)
        wbuf = tgt.dev_bufs.get(w.addr)
        boff = base - w.addr   # byte offset inside the window buffer
        if (wbuf is not None and not _noncanonical(uncomp)
                and np.dtype(wbuf.dtype) == uncomp
                and boff % uncomp.itemsize == 0):
            src = self.dev_bufs.get(desc.addr_0)
            if (src is not None and src.size >= desc.count
                    and np.dtype(src.dtype) == uncomp
                    and not (desc.compression
                             & Compression.OP0_COMPRESSED)):
                payload = src.jax.reshape(-1)[:desc.count]  # zero-copy
            else:
                host = self._read_operand(desc.addr_0, desc.count, desc,
                                          Compression.OP0_COMPRESSED)
                payload = jax.device_put(np.array(host, copy=True),
                                         self.my_device)
            if wire is not None:
                payload = payload.astype(wire)   # narrow BEFORE the hop
            if tgt.my_device != self.my_device:
                payload = jax.device_put(payload, tgt.my_device)
            if payload.dtype != jnp.dtype(uncomp):
                payload = payload.astype(uncomp)  # decompress on landing
            wbuf._rebind(_window_put_prog(wbuf.jax, payload,
                                          boff // uncomp.itemsize))
            return 0
        host = self._read_operand(desc.addr_0, desc.count, desc,
                                  Compression.OP0_COMPRESSED)
        if wire is not None:
            host = host.astype(wire).astype(uncomp)  # wire round-trip
        data = np.ascontiguousarray(host, dtype=uncomp).view(np.uint8)
        if wbuf is not None:
            raw = np.asarray(wbuf.jax).reshape(-1).view(np.uint8).copy()
            raw[boff:boff + nbytes] = data
            tgt._rebind_dev(wbuf, raw.view(np.dtype(wbuf.dtype)))
            return 0
        tgt.mem.write(base, host.astype(uncomp, copy=False))
        return 0

    def _do_get(self, desc: CallDescriptor, comm: Communicator) -> int:
        """One-sided read: pull ``count`` elements from byte ``offset``
        of a window on the source rank into the local result buffer (the
        source posts no matching call). Device-resident windows read
        zero-copy and the payload crosses device-to-device."""
        src_dev = self._rma_peer(desc, comm)
        uncomp = np.dtype(desc.arithcfg.uncompressed_dtype)
        nbytes = desc.count * uncomp.itemsize
        base = src_dev.windows.resolve(desc.tag, desc.addr_1, nbytes)
        wire = (desc.arithcfg.compressed_dtype
                if desc.compression & Compression.ETH_COMPRESSED else None)
        w = src_dev.windows.get(desc.tag)
        wbuf = src_dev.dev_bufs.get(w.addr)
        boff = base - w.addr
        if (wbuf is not None and not _noncanonical(uncomp)
                and np.dtype(wbuf.dtype) == uncomp
                and boff % uncomp.itemsize == 0):
            off = boff // uncomp.itemsize
            payload = wbuf.jax.reshape(-1)[off:off + desc.count]
            if wire is not None:
                payload = payload.astype(wire)   # narrow BEFORE the hop
            if src_dev.my_device != self.my_device:
                payload = jax.device_put(payload, self.my_device)
            if payload.dtype != jnp.dtype(uncomp):
                payload = payload.astype(uncomp)
            dst = self.dev_bufs.get(desc.addr_2)
            if (dst is not None and dst.size == desc.count
                    and not (desc.compression
                             & Compression.RES_COMPRESSED)):
                self._rebind_dev(dst, payload)   # stays on device
            else:
                self._write_result(desc.addr_2, np.asarray(payload), desc)
            return 0
        if wbuf is not None:
            raw = np.asarray(wbuf.jax).reshape(-1).view(np.uint8)
            host = np.frombuffer(raw[boff:boff + nbytes].tobytes(), uncomp)
        else:
            host = src_dev.mem.read(base, desc.count, uncomp)
        if wire is not None:
            host = host.astype(wire).astype(uncomp)
        self._write_result(desc.addr_2, host, desc)
        return 0

    # -- collective rendezvous --------------------------------------------
    def _do_collective(self, desc: CallDescriptor, comm: Communicator,
                       handle: CallHandle,
                       defer_launch: bool = False) -> None:
        """Deposit this rank's call; the group-completing arrival launches
        and completes EVERY member's handle directly. No member ever
        blocks a thread waiting for results — once a group is claimed it
        structurally cannot be timed out mid-execution (the round-2 waiter
        bug class), and the only parked state is an incomplete group,
        which the context's deadline sweeper fails with
        RECEIVE_TIMEOUT_ERROR per member (the old per-waiter timeout
        semantics)."""
        ctx = self.ctx
        # the deposit's parked lifetime is bounded by the CALLER's absolute
        # deadline when one was imposed (call_sync timeout plumbed via the
        # desc, measured from call_sync entry): a collective that timed out
        # for its caller must not be completed later by late-arriving peers
        deadline = (desc.deadline if desc.deadline is not None
                    else time.monotonic() + self.timeout)
        with ctx._lock:
            # index assignment under the ctx lock: deposit order IS the
            # per-rank matching order (MPI program-order matching)
            idx = self._coll_index[desc.comm_id]
            self._coll_index[desc.comm_id] += 1
            key = (desc.comm_id, idx)
            group = ctx._pending.setdefault(key, {})
            # an expired member must not count toward completion (its
            # caller's wait already raised): fail it here rather than
            # racing the sweeper's next poll — otherwise a late arrival
            # could claim the group and mutate the expired caller's
            # buffers after its timeout. Completion runs OUTSIDE the
            # lock (sweeper discipline): complete() runs done-callbacks
            # synchronously, and one that re-enters the backend would
            # deadlock on the non-reentrant ctx lock.
            now = time.monotonic()
            expired = [group.pop(r)[1]
                       for r in [r for r, (_, _, dl) in group.items()
                                 if dl <= now]]
            group[comm.local_rank] = (desc, handle, deadline)
            is_last = len(group) == comm.size
            if is_last:
                # claim: execution happens OUTSIDE the lock so collectives
                # of disjoint communicators run concurrently
                del ctx._pending[key]
            else:
                ctx._ensure_sweeper()
        for h in expired:
            h.complete(int(ErrorCode.RECEIVE_TIMEOUT_ERROR),
                       exception=ACCLError(
                           int(ErrorCode.RECEIVE_TIMEOUT_ERROR),
                           "collective member deadline expired"))
        if not is_last:
            # the synchronous-call path (call_sync/_run_one's caller)
            # blocks in handle.wait(); async callers hold the handle
            return None
        if defer_launch:
            # async last arrival: the heavy launch must not run in the
            # submitter's thread (call_async would block for the whole
            # collective) — hop it to this rank's worker. The inflight
            # slot keeps later same-rank calls FIFO behind it.
            self._inflight_add()
            self._calls.put(lambda: self._finish_group(group, comm))
            return None
        self._finish_group(group, comm)
        return None

    def _finish_group(self, group: dict, comm: Communicator) -> None:
        """Launch a claimed group and complete EVERY member's handle."""
        err = int(ErrorCode.INVALID_CALL)
        exc_out: BaseException | None = None
        try:
            descs = [group[r][0] for r in range(comm.size)]
            err = self._launch(descs, comm)
        except Exception as exc:  # noqa: BLE001
            # observability: don't bury the cause — attributable to the
            # launching rank, capturable via the accl_tpu logger
            log.error("rank %s: collective group launch failed",
                      getattr(self, "rank", "-"), exc_info=True,
                      extra={"rank": getattr(self, "rank", "-")})
            exc_out = exc
        finally:
            # completion runs in a finally so a claimed group ALWAYS
            # resolves — any escape path (desc-assembly errors,
            # BaseExceptions) that skipped it would wedge every waiter
            for _, h, _dl in group.values():
                h.complete(err, exception=exc_out)

    def _launch(self, descs: list, comm: Communicator) -> int:
        """Execute one collective for all member ranks (no locks held)."""
        ctx = self.ctx
        d0 = descs[0]
        op = d0.scenario
        if any(d.scenario != op or d.count != d0.count for d in descs):
            return int(ErrorCode.INVALID_CALL)
        count = d0.count
        W = comm.size
        cfg = d0.arithcfg
        wire = (cfg.compressed_dtype
                if d0.compression & Compression.ETH_COMPRESSED else None)
        devs = [ctx.devices[comm.ranks[r].global_rank] for r in range(W)]

        def read_all(addr_of, n):
            rows = []
            for r, d in enumerate(descs):
                addr = addr_of(d)
                if addr:
                    rows.append(devs[r]._read_operand(
                        addr, n, d, Compression.OP0_COMPRESSED))
                else:
                    rows.append(np.zeros(n, cfg.uncompressed_dtype))
            return rows

        coll, alg = ctx.coll_for(comm), ctx.algorithm
        # per-call selector (CollectiveAlgorithm) overrides the context
        # default: ring variants lower to the shard_map ppermute rings,
        # everything else to XLA's native collectives. Validation uses the
        # same table as the emulator tiers so invalid (op, algorithm) pairs
        # fail identically everywhere.
        try:
            check_algorithm(op.name, d0.algorithm)
        except ValueError:
            return int(ErrorCode.INVALID_CALL)
        if d0.algorithm in (CollectiveAlgorithm.RING,
                            CollectiveAlgorithm.FUSED_RING):
            alg = "ring"
        elif d0.algorithm != CollectiveAlgorithm.AUTO:
            alg = "xla"
        # block-scaled quantized wire (compress_dtype=..., block_scale=True
        # at the driver): the dense ring collectives take the fused Pallas
        # quantize->combine->requant lane — qblock selects it and pins the
        # ppermute ring (the only shape the fused codec hops ride). Other
        # ops fall back to the FULL-PRECISION wire: their per-tensor cast
        # lanes would silently truncate (int8) or re-scale per tensor
        # (fp8), neither of which is block-scaled semantics.
        qblock = 0
        if wire is not None and d0.compression & Compression.BLOCK_SCALED:
            from ..quant import DEFAULT_BLOCK
            from ..parallel.collectives import BS_WIRE_DTYPE_NAMES
            if (op in (CCLOp.allreduce, CCLOp.reduce_scatter,
                       CCLOp.allgather)
                    and _wire_name(wire) in BS_WIRE_DTYPE_NAMES):
                qblock = int(getattr(cfg, "quant_block", 0)
                             or DEFAULT_BLOCK)
                alg = "ring"
            else:
                wire = None
        # rooted ops default to the hierarchical 2D-mesh tree when the comm
        # has 2D structure — O(outer+inner) hop fan-out instead of the
        # psum/all_gather-class traffic of the masked 1-D lowerings (which
        # cost allreduce/allgather bandwidth regardless of root). Explicit
        # ROUND_ROBIN/RING selectors keep the 1-D path; the explicit TREE
        # selector (legal for bcast/gather/reduce, VALID_ALGORITHMS) pins
        # the tree — scatter reaches it via AUTO only. Rooted reduce
        # rides the tree only uncompressed: the tree has no
        # wire-compression lanes, and the compressed 1-D path's
        # decompress-before-arith numerics must win.
        rooted = (CCLOp.bcast, CCLOp.scatter, CCLOp.gather, CCLOp.reduce)
        use_tree = (op in rooted
                    and (d0.algorithm == CollectiveAlgorithm.AUTO
                         or (d0.algorithm == CollectiveAlgorithm.TREE
                             and op in (CCLOp.bcast, CCLOp.gather,
                                        CCLOp.reduce)))
                    and not (op == CCLOp.reduce and wire is not None))
        tree = ctx.tree_for(comm) if use_tree else None
        root = d0.root_src_dst
        if op == CCLOp.barrier:
            return 0  # rendezvous above IS the barrier

        # -- device-resident fast path (to_from_fpga=False parity) --------
        # When every member rank's src AND dst buffer is device-resident
        # with exact geometry, the dense collectives skip host staging
        # entirely: per-rank arrays assemble into the flat global via
        # make_array_from_single_device_arrays, one cached program runs,
        # and result shards rebind each rank's dst — zero host copies.
        dense_fast = {CCLOp.allreduce: (count, count),
                      CCLOp.allgather: (count, W * count),
                      CCLOp.reduce_scatter: (W * count, count),
                      CCLOp.alltoall: (W * count, W * count)}
        if op in dense_fast:
            n_in, n_out = dense_fast[op]
            res = self._launch_device_fast(op, descs, devs, coll, alg,
                                           wire, cfg, n_in, n_out, d0,
                                           qblock)
            if res is not None:
                return res
        if op in rooted:
            res = self._launch_device_rooted(op, descs, devs, coll, alg,
                                             cfg, count, root, d0, wire)
            if res is not None:
                return res

        if op == CCLOp.allreduce:
            x = coll.shard(read_all(lambda d: d.addr_0, count))
            out = np.asarray(coll.allreduce(x, func=d0.function,
                                            algorithm=alg, wire_dtype=wire,
                                            qblock=qblock))
            for r, d in enumerate(descs):
                devs[r]._write_result(d.addr_2, out[r], d)
            return 0
        if op == CCLOp.reduce:
            rows = read_all(lambda d: d.addr_0, count)
            if tree is not None:
                out = np.asarray(tree.reduce(tree.shard(rows), root=root,
                                             func=d0.function))
            else:
                out = np.asarray(coll.reduce(coll.shard(rows), root=root,
                                             func=d0.function,
                                             wire_dtype=wire))
            devs[root]._write_result(descs[root].addr_2, out[root],
                                     descs[root])
            return 0
        if op == CCLOp.reduce_scatter:
            x = coll.shard(read_all(lambda d: d.addr_0, W * count))
            out = np.asarray(coll.reduce_scatter(x, func=d0.function,
                                                 algorithm=alg,
                                                 wire_dtype=wire,
                                                 qblock=qblock))
            for r, d in enumerate(descs):
                devs[r]._write_result(d.addr_2, out[r], d)
            return 0
        if op == CCLOp.allgather:
            x = coll.shard(read_all(lambda d: d.addr_0, count))
            out = np.asarray(coll.allgather(x, algorithm=alg,
                                            wire_dtype=wire, qblock=qblock))
            for r, d in enumerate(descs):
                devs[r]._write_result(d.addr_2, out[r], d)
            return 0
        if op == CCLOp.bcast:
            rows = read_all(lambda d: d.addr_0, count)
            if tree is not None:
                out = np.asarray(tree.bcast(tree.shard(rows), root=root,
                                            wire_dtype=wire))
            else:
                out = np.asarray(coll.bcast(coll.shard(rows), root=root,
                                            wire_dtype=wire))
            for r, d in enumerate(descs):
                if r != root:  # root's own buffer never crossed the wire
                    devs[r]._write_result(d.addr_0, out[r], d)
            return 0
        if op == CCLOp.scatter:
            rows = read_all(lambda d: d.addr_0, W * count)
            if tree is not None:
                out = np.asarray(tree.scatter(tree.shard(rows), root=root,
                                              wire_dtype=wire))
            else:
                out = np.asarray(coll.scatter(coll.shard(rows), root=root,
                                              wire_dtype=wire))
            for r, d in enumerate(descs):
                devs[r]._write_result(d.addr_2, out[r], d)
            return 0
        if op == CCLOp.gather:
            rows = read_all(lambda d: d.addr_0, count)
            if tree is not None:
                out = np.asarray(tree.gather(tree.shard(rows), root=root,
                                             wire_dtype=wire))
            else:
                out = np.asarray(coll.gather(coll.shard(rows), root=root,
                                             wire_dtype=wire))
            devs[root]._write_result(descs[root].addr_2, out[root],
                                     descs[root])
            return 0
        if op == CCLOp.alltoall:
            x = coll.shard(read_all(lambda d: d.addr_0, W * count))
            # the program casts chunks on the wire and restores each
            # rank's self chunk exact (emulator-tier wire_q_except parity)
            out = np.asarray(coll.alltoall(x, wire_dtype=wire))
            for r, d in enumerate(descs):
                devs[r]._write_result(d.addr_2, out[r], d)
            return 0
        return int(ErrorCode.COLLECTIVE_NOT_IMPLEMENTED)

    def _launch_device_fast(self, op, descs, devs, coll, alg, wire, cfg,
                            n_in: int, n_out: int, d0,
                            qblock: int = 0) -> int | None:
        """Zero-host-staging dense collective. Returns None when any
        member's operands disqualify (not device-resident, geometry or
        dtype mismatch, host-side compression flags) — the caller then
        takes the staged path. OP0/RES_COMPRESSED disqualify because a
        device buffer has one storage dtype (no compressed host mirror);
        ETH (wire) compression stays eligible — it lives inside the
        program."""
        bad = (Compression.OP0_COMPRESSED | Compression.OP1_COMPRESSED
               | Compression.RES_COMPRESSED)
        uncomp = np.dtype(cfg.uncompressed_dtype)
        srcs, dsts = [], []
        for r, d in enumerate(descs):
            if d.compression & bad:
                return None
            sb = devs[r].dev_bufs.get(d.addr_0)
            db = devs[r].dev_bufs.get(d.addr_2)
            if (sb is None or db is None
                    or sb.size != n_in or db.size != n_out
                    or sb.dtype != uncomp or db.dtype != uncomp):
                return None
            srcs.append(sb.jax if sb.jax.ndim == 1 else sb.jax.reshape(-1))
            dsts.append(db)
        func = (d0.function if op in (CCLOp.allreduce, CCLOp.reduce_scatter)
                else ReduceFunc.SUM)
        x = self.ctx.assemble_flat(coll, srcs)
        out = coll._program_flat(op.name, alg, func, _wire_name(wire),
                                 None, qblock)(x)
        self._rebind_out_shards(coll, out, dict(enumerate(dsts)), devs)
        return 0

    def _rebind_out_shards(self, coll, out, dst_map: dict, devs):
        """Rebind a flat program output's per-rank shards onto the
        destination device buffers in ``dst_map`` (rank -> buffer; ranks
        absent from the map — e.g. non-roots of a gather — are dropped
        without touching any buffer).

        Shard objects are expensive to build (index/device per shard,
        ~15us each); the position->rank order is a pure function of the
        (fixed) flat sharding, so compute it once per mesh and reuse.
        jax.Array._arrays is private, so the first call also VERIFIES it
        matches addressable_shards device-for-device before trusting it
        on later calls — if the contract ever changes (or the attribute
        disappears) we stay on the public API instead of silently
        scattering results to the wrong ranks."""
        order = coll._cache.get("shard_order")
        if order is None:
            shards = list(out.addressable_shards)
            order = [(s.index[0].start or 0) * len(shards)
                     // out.shape[0] for s in shards]
            coll._cache["shard_order"] = order
            arrs = getattr(out, "_arrays", None)
            coll._cache["shard_arrays_ok"] = bool(
                arrs is not None and len(arrs) == len(shards)
                and all(getattr(a, "device", None) == s.device
                        for a, s in zip(arrs, shards)))
            datas = [s.data for s in shards]
        elif coll._cache.get("shard_arrays_ok"):
            datas = out._arrays
        else:
            datas = [s.data for s in out.addressable_shards]
        for pos, r in enumerate(order):
            db = dst_map.get(r)
            if db is None:
                continue
            # eligibility proved size+dtype; only a non-1-D dst needs the
            # general rebind (reshape), so the common case is one pointer
            # swap
            if len(db._shape) == 1:
                db._rebind(datas[pos])
            else:
                devs[r]._rebind_dev(db, datas[pos])

    def _launch_device_rooted(self, op, descs, devs, coll, alg, cfg,
                              count: int, root: int, d0,
                              wire=None) -> int | None:
        """Zero-host-staging ROOTED collective (bcast/scatter/gather/
        reduce) — the reference's ``to_from_fpga=False`` mode applies to
        every op, not just the dense four (VERDICT r4 item 3). Buffer
        geometry is asymmetric: only the ranks that own data on each
        side must be device-resident; a scatter's non-root "sources"
        don't exist and ride in as cached device zeros. Returns None
        when the involved buffers disqualify (caller takes the staged
        path). ETH (wire) compression rides inside the program, like
        the dense fast path."""
        bad = (Compression.OP0_COMPRESSED | Compression.OP1_COMPRESSED
               | Compression.RES_COMPRESSED)
        if any(d.compression & bad for d in descs):
            return None
        uncomp = np.dtype(cfg.uncompressed_dtype)
        W = len(descs)

        def resident(r, addr, n):
            """Device buffer at (rank, addr) with exact geometry, else
            None (disqualifies)."""
            b = devs[r].dev_bufs.get(addr)
            if b is None or b.size != n or b.dtype != uncomp:
                return None
            return b

        def flat(b):
            return b.jax if b.jax.ndim == 1 else b.jax.reshape(-1)

        if op == CCLOp.bcast:
            # in-place on addr_0 everywhere: root's is the source, every
            # other rank's is the destination
            bufs = [resident(r, d.addr_0, count)
                    for r, d in enumerate(descs)]
            if any(b is None for b in bufs):
                return None
            srcs = [flat(b) for b in bufs]
            dst_map = {r: b for r, b in enumerate(bufs) if r != root}
        elif op == CCLOp.reduce:
            bufs = [resident(r, d.addr_0, count)
                    for r, d in enumerate(descs)]
            rootdst = resident(root, descs[root].addr_2, count)
            if any(b is None for b in bufs) or rootdst is None:
                return None
            srcs = [flat(b) for b in bufs]
            dst_map = {root: rootdst}
        elif op == CCLOp.scatter:
            rootsrc = resident(root, descs[root].addr_0, W * count)
            dsts = [resident(r, d.addr_2, count)
                    for r, d in enumerate(descs)]
            if rootsrc is None or any(b is None for b in dsts):
                return None
            # non-root input shards are never read by the binomial
            # schedule's first hop from root; cached device zeros keep
            # the flat assembly uniform without host traffic
            srcs = [flat(rootsrc) if r == root
                    else self.ctx.zero_shard(coll.device_list[r],
                                             W * count, uncomp)
                    for r in range(W)]
            dst_map = dict(enumerate(dsts))
        elif op == CCLOp.gather:
            bufs = [resident(r, d.addr_0, count)
                    for r, d in enumerate(descs)]
            rootdst = resident(root, descs[root].addr_2, W * count)
            if any(b is None for b in bufs) or rootdst is None:
                return None
            srcs = [flat(b) for b in bufs]
            dst_map = {root: rootdst}
        else:
            return None

        x = self.ctx.assemble_flat(coll, srcs)
        func = d0.function if op == CCLOp.reduce else ReduceFunc.SUM
        out = coll._program_flat(op.name, alg, func, _wire_name(wire),
                                 root)(x)
        self._rebind_out_shards(coll, out, dst_map, devs)
        return 0


def tpu_world(world_size: int | None = None, platform: str | None = None,
              algorithm: str = "xla", timeout: float = DEFAULT_TIMEOUT_S,
              tuner=None) -> list:
    """Create ACCL instances backed by a device mesh (one rank per device).

    The TPU-tier analog of testing.emu_world. ``tuner`` (one shared
    :class:`~accl_tpu.tuner.Tuner`) resolves AUTO selectors by
    size/topology — same rank-agreement rule as emu_world."""
    from ..accl import ACCL
    from ..communicator import Communicator, Rank
    ctx = TpuContext(world_size, platform=platform, algorithm=algorithm)
    W = ctx.world_size
    accls = []
    for r in range(W):
        comm = Communicator(ranks=[Rank() for _ in range(W)], local_rank=r)
        accls.append(ACCL(ctx.device(r), comm, timeout=timeout,
                          tuner=tuner))
    return accls
