"""Abstract device backend interface.

Parity: the reference driver's device abstraction is the MMIO+call transport
pair — real hardware (pynq Overlay + hostctrl kernel) or SimDevice (ZMQ) —
behind one ``call/start/read/write`` surface (driver/pynq/accl.py:33-159).
Ours is a clean ABC the driver talks to; buffers and call descriptors are
the currency.
"""

from __future__ import annotations

import abc
from typing import Sequence

from ..buffer import ACCLBuffer
from ..call import CallDescriptor, CallHandle
from ..communicator import Communicator


class Device(abc.ABC):
    """One rank's execution backend."""

    @abc.abstractmethod
    def register_buffer(self, buf: ACCLBuffer): ...

    @abc.abstractmethod
    def deregister_buffer(self, buf: ACCLBuffer): ...

    def sync_to_device(self, buf: ACCLBuffer):
        """Host->device copy; default no-op for host-memory backends."""

    def sync_from_device(self, buf: ACCLBuffer):
        """Device->host copy; default no-op for host-memory backends."""

    @abc.abstractmethod
    def call_async(self, desc: CallDescriptor,
                   waitfor: Sequence[CallHandle] = ()) -> CallHandle: ...

    def call_sync(self, desc: CallDescriptor,
                  waitfor: Sequence[CallHandle] = (),
                  timeout: float | None = None):
        return self.call_async(desc, waitfor).wait(timeout)

    @abc.abstractmethod
    def configure_communicator(self, comm: Communicator): ...

    @abc.abstractmethod
    def set_timeout(self, timeout: float): ...

    @abc.abstractmethod
    def set_max_segment_size(self, nbytes: int): ...

    def preferred_segment_size(self) -> int:
        """Largest segment this backend can accept; the driver defaults the
        max segment size to this at init (reference: the driver sets
        max_segment_size = rx bufsize at bring-up, accl.py:380)."""
        from ..constants import DEFAULT_MAX_SEGMENT_SIZE
        return DEFAULT_MAX_SEGMENT_SIZE

    def soft_reset(self):
        """Parity: HOUSEKEEP_SWRST (ccl_offload_control.c:1244-1247)."""

    def deinit(self):
        """Release backend resources (driver deinit, accl.py:421-433)."""
