"""Abstract device backend interface.

Parity: the reference driver's device abstraction is the MMIO+call transport
pair — real hardware (pynq Overlay + hostctrl kernel) or SimDevice (ZMQ) —
behind one ``call/start/read/write`` surface (driver/pynq/accl.py:33-159).
Ours is a clean ABC the driver talks to; buffers and call descriptors are
the currency.
"""

from __future__ import annotations

import abc
import dataclasses
import threading
import time
from typing import Sequence

from ..buffer import ACCLBuffer
from ..call import CallDescriptor, CallHandle
from ..communicator import Communicator
from ..tracing import health_rows


def _device_metrics_rows(dev: "Device"):
    """Shared metrics collector for one rank's backend: reports whichever
    health surfaces the backend actually has (rx pool, move executor,
    plan cache) as registry rows — one mapping (:func:`tracing.health_rows`,
    shared with the daemon collector) for every Device subclass so
    backends can never drift in how they report. Polled only at snapshot
    time (:meth:`~accl_tpu.tracing.MetricsRegistry.snapshot`)."""
    # "tier" disambiguates from _daemon_metrics_rows' identical families:
    # one process can host an in-process device world AND spawn_world
    # daemons, and {rank} alone would merge their series (last-write-wins
    # gauges, summed counters) into one indistinguishable key
    labels = {"rank": getattr(dev, "_metrics_rank", -1), "tier": "device"}
    # world tag (emu backends): rank+tier alone would merge two
    # concurrently live same-shape worlds' series — counters would sum,
    # gauges would last-write-win. Shares the fabric's ctx_seq so device,
    # driver and fabric rows of one world carry the same tag.
    ctx_seq = getattr(getattr(getattr(dev, "ctx", None), "fabric", None),
                      "ctx_seq", None)
    if ctx_seq is not None:
        labels["ctx"] = ctx_seq
    yield from health_rows(dev, labels)


class Device(abc.ABC):
    """One rank's execution backend."""

    # Optional attached Tuner (accl_tpu/tuner): the driver sets this when
    # constructed with ``tuner=`` so engine-level AUTO resolution
    # (moveengine.expand_call via MoveContext.tuner) can consult it for
    # descriptors that still carry AUTO when they reach the engine.
    tuner = None

    def register_metrics(self, rank: int):
        """Attach this backend to the process-wide metrics registry
        (weakly — the collector dies with the device). Backends call it
        once they own their pool/executor/plan-cache surfaces."""
        from ..tracing import METRICS
        self._metrics_rank = rank
        METRICS.register_collector(self, _device_metrics_rows)

    def topology(self):
        """Link-level descriptor of this backend's fabric tier, feeding
        the tuner's cost model (tuner/cost.py). Backends override with
        calibrated per-tier figures; this generic default only has to
        order algorithms sanely."""
        from ..tuner.cost import Topology
        return Topology(world_size=0, alpha_us=50.0, beta_gbps=1.0,
                        tier="generic")

    def auto_resolvable_ops(self):
        """Ops whose AUTO the driver may resolve through the tuner before
        issue; None (the default) means every op with an algorithm axis.
        A backend whose own AUTO handling beats anything the selector
        enum can express restricts this — the TPU tier's hierarchical
        2D-mesh tree for rooted scatter/gather/reduce has no enum value,
        so resolving their AUTO to RING/ROUND_ROBIN would silently
        degrade it (device/tpu.py overrides)."""
        return None

    # -- shared inline fast-path gate (used by Emu/Sim backends) ----------
    # A backend that can retire a synchronous call in the caller's thread
    # guards the path with one counter: >0 means calls are queued or not
    # yet past the point that fixes their submission order (each backend
    # documents where it decrements). The gate is shared so the
    # concurrency-sensitive pattern exists once.

    # class-level guard: creation of the per-instance gate must itself be
    # race-free (two first-callers racing the lazy init would each build a
    # lock and lose an increment)
    _inline_init_mu = threading.Lock()

    def _inline_state(self):
        mu = getattr(self, "_inline_mu", None)
        if mu is None:
            with Device._inline_init_mu:
                mu = getattr(self, "_inline_mu", None)
                if mu is None:
                    self._inline_inflight = 0
                    mu = self._inline_mu = threading.Lock()
        return mu

    def _inline_begin(self, waitfor: Sequence[CallHandle]) -> bool:
        """True iff the device is idle and every dependency retired —
        the caller may run inline and MUST call :meth:`_inflight_done`
        when finished."""
        if not all(dep.done() for dep in waitfor):
            return False
        with self._inline_state():
            if self._inline_inflight != 0:
                return False
            self._inline_inflight += 1
            return True

    def _inflight_add(self):
        with self._inline_state():
            self._inline_inflight += 1

    def _inflight_done(self):
        with self._inline_state():
            self._inline_inflight -= 1

    @abc.abstractmethod
    def register_buffer(self, buf: ACCLBuffer): ...

    @abc.abstractmethod
    def deregister_buffer(self, buf: ACCLBuffer): ...

    def sync_to_device(self, buf: ACCLBuffer):
        """Host->device copy; default no-op for host-memory backends."""

    def sync_from_device(self, buf: ACCLBuffer):
        """Device->host copy; default no-op for host-memory backends."""

    @abc.abstractmethod
    def call_async(self, desc: CallDescriptor,
                   waitfor: Sequence[CallHandle] = (), *,
                   inline_ok: bool = False) -> CallHandle:
        """Submit a call; returns its handle.

        ``inline_ok`` is a latency hint: the caller will immediately block
        on the handle (a synchronous driver call), so a backend MAY retire
        the call in the calling thread instead of a worker. It must never
        be set for calls the caller treats as asynchronous — an inline
        blocking recv would stall (or deadlock) a symmetric async program.
        """

    def call_sync(self, desc: CallDescriptor,
                  waitfor: Sequence[CallHandle] = (),
                  timeout: float | None = None):
        # inline retirement blocks inside call_async and would bypass a
        # local timeout bound, so only hint inline when none is imposed
        if timeout is not None:
            # plumb the caller's bound into the descriptor as an ABSOLUTE
            # deadline (from this moment — queue or dependency delay must
            # not extend it) so backend rendezvous deadlines (TPU-tier
            # deposits) honor it: a TimeoutError here must imply the call
            # will not run later
            desc = dataclasses.replace(
                desc, deadline=time.monotonic() + timeout)
        return self.call_async(desc, waitfor,
                               inline_ok=timeout is None).wait(timeout)

    @abc.abstractmethod
    def configure_communicator(self, comm: Communicator,
                               tenant: str | None = None):
        """Register a communicator. ``tenant`` optionally groups it under
        a multi-tenant service tenant (accl_tpu/service) — backends
        without a service layer may ignore it, but must accept it."""

    @abc.abstractmethod
    def set_timeout(self, timeout: float): ...

    @abc.abstractmethod
    def set_max_segment_size(self, nbytes: int): ...

    def preferred_segment_size(self) -> int:
        """Largest segment this backend can accept; the driver defaults the
        max segment size to this at init (reference: the driver sets
        max_segment_size = rx bufsize at bring-up, accl.py:380)."""
        from ..constants import DEFAULT_MAX_SEGMENT_SIZE
        return DEFAULT_MAX_SEGMENT_SIZE

    # -- external-kernel stream ports --------------------------------------
    def push_stream(self, data):
        """Feed the rank's stream-in port (OP0_STREAM operand source;
        reference: the external-kernel AXIS port, SWITCH_M_BYPASS).
        Backends without a stream port raise STREAM_NOT_SUPPORTED — never
        silently ignore the flag."""
        from ..constants import ACCLError, ErrorCode
        raise ACCLError(int(ErrorCode.STREAM_NOT_SUPPORTED),
                        f"{type(self).__name__} has no stream port; fuse "
                        "producers into the device program instead")

    def pop_stream(self, timeout: float = 0.0, count: int | None = None):
        """Read from the stream-out port: ``count`` elements, or the next
        produced entry whole when ``count`` is None (RES_STREAM sink)."""
        from ..constants import ACCLError, ErrorCode
        raise ACCLError(int(ErrorCode.STREAM_NOT_SUPPORTED),
                        f"{type(self).__name__} has no stream port")

    # -- device-resident buffers (to_from_fpga=False fast path) ------------
    def adopt_device_array(self, arr):
        """Accept a live device array for a device-resident buffer.
        Backends without device arrays reject — never silently fall back
        to a host mirror the caller believes is zero-copy."""
        raise ValueError(
            f"{type(self).__name__} has no device-array storage; use a "
            "host buffer (device-resident mode is a TPU-backend feature)")

    def make_device_array(self, shape, dtype, init=None):
        """Allocate a fresh device array on this rank's device (zeros, or
        ``init`` contents) for a device-resident buffer."""
        raise ValueError(
            f"{type(self).__name__} has no device-array storage; use a "
            "host buffer (device-resident mode is a TPU-backend feature)")

    # -- one-sided RMA windows (accl_tpu/rma) ------------------------------
    def register_window(self, wid: int, addr: int, nbytes: int):
        """Register ``[addr, addr+nbytes)`` as one-sided window ``wid``
        so peers can put/get against it. Backends without an RMA engine
        reject — a put toward an unregistered tier must fail at
        registration time, not as a mystery timeout."""
        from ..constants import ACCLError, ErrorCode
        raise ACCLError(int(ErrorCode.COLLECTIVE_NOT_IMPLEMENTED),
                        f"{type(self).__name__} has no one-sided RMA "
                        "engine (emulator/daemon tiers only)")

    def deregister_window(self, wid: int):
        """Remove a window registration (no-op when absent)."""

    def poll_notifications(self, window: int, max_records: int = 64):
        """Drain put-with-notify completion records for ``window``
        (``rma.notify.ANY_WINDOW`` = all). Must be purely local — no
        wire traffic, no collective. Backends without an RMA engine
        simply have nothing pending."""
        return []

    # -- elastic membership (ACCL.grow_communicator) -----------------------
    def join_handshake(self, comm: Communicator, timeout: float) -> int:
        """Bootstrap handshake of a grown communicator: block until every
        member of ``comm`` has announced itself alive and agreeing on the
        membership, or ``timeout`` expires. Returns 0 on success or a
        typed error word (JOIN_FAILED, OR-ed with RECEIVE_TIMEOUT_ERROR
        on a plain timeout). Single-controller backends (TPU mesh tier)
        have no independent peers to synchronize with — membership is a
        host-side fact there — so the default is immediate success; the
        emulator and daemon tiers exchange JOIN_STRM hello frames."""
        return 0

    def abort_comm(self, comm_id: int, err: int):
        """Containment hook for an application-driven revoke: abort
        in-flight programs on ``comm_id`` with the typed error NOW and
        latch it for pending recvs, instead of letting async handles
        ride out their full receive deadline. Default no-op (backends
        without an abortable executor surface the revocation at the
        next call through the driver's revoked-comm check)."""

    def soft_reset(self):
        """Parity: HOUSEKEEP_SWRST (ccl_offload_control.c:1244-1247)."""

    def deinit(self):
        """Release backend resources (driver deinit, accl.py:421-433)."""

    # -- runtime config calls ----------------------------------------------
    def segment_size_bound(self) -> int | None:
        """Upper bound a config call may set the segment size to; None =
        unbounded (the emulator bounds it by its rx buffer size, mirroring
        segments-must-fit-spare-buffers, reference accl.py:660-667)."""
        return None

    def apply_config(self, desc: CallDescriptor) -> int:
        """Shared ACCL_CONFIG dispatch for in-process backends
        (c:1240-1283): subfunction in ``tag``, value in ``count`` (ms for
        timeout, bytes for segment size). The in-process fabrics have no
        ports/sessions/stack to manage, so the connection subfunctions
        succeed as no-ops — like the reference's loopback builds where the
        dummy stack always accepts. The socket daemons implement the full
        surface (emulator/daemon.py, native/cclo_emud.cpp)."""
        from ..constants import CfgFunc, ErrorCode
        try:
            fn = CfgFunc(desc.tag)
        except ValueError:
            return int(ErrorCode.INVALID_CALL)
        val = int(desc.count)
        if fn == CfgFunc.reset_periph:
            self.soft_reset()
            return 0
        if fn == CfgFunc.set_timeout:
            self.set_timeout(val / 1000.0)
            return 0
        if fn == CfgFunc.set_max_segment_size:
            bound = self.segment_size_bound()
            if bound is not None and val > bound:
                return int(ErrorCode.DMA_SIZE_ERROR)
            self.max_segment_size = val
            return 0
        if fn == CfgFunc.start_profiling:
            self.profiling = True
            return 0
        if fn == CfgFunc.end_profiling:
            self.profiling = False
            return 0
        if fn in (CfgFunc.enable_pkt, CfgFunc.open_port, CfgFunc.open_con,
                  CfgFunc.close_con, CfgFunc.set_stack_type):
            return 0
        return int(ErrorCode.INVALID_CALL)
