"""Package-level logging: one ``accl_tpu`` logger hierarchy, rank-tagged.

The reference's crash story is a process per rank whose stderr mpirun
prefixes with the rank — a bare ``traceback.print_exc()`` there is
attributable for free. The TPU rebuild runs many ranks as THREADS of one
process (the in-process emu world, ``spawn_world`` daemons), so unowned
stderr tracebacks interleave into soup. Every library log site therefore
goes through ``get_logger(...)`` (a child of the ``accl_tpu`` logger) and
carries the owning rank in the message; embedders capture or silence the
whole package with one ``logging.getLogger("accl_tpu")`` handle.

No handler is installed at import (library etiquette): Python's
last-resort handler prints WARNING+ to stderr out of the box, and pytest's
logging capture sees everything. ``basic_config()`` opts into a
rank-tagged stderr handler for standalone processes (the daemon's
``__main__`` calls it).
"""

from __future__ import annotations

import logging

__all__ = ["get_logger", "basic_config", "RankTagFilter"]

ROOT_NAME = "accl_tpu"


def get_logger(subname: str | None = None) -> logging.Logger:
    """The package logger, or the ``accl_tpu.<subname>`` child. Accepts a
    ``__name__`` already under the package unchanged."""
    if not subname:
        return logging.getLogger(ROOT_NAME)
    if subname.startswith(ROOT_NAME):
        return logging.getLogger(subname)
    return logging.getLogger(f"{ROOT_NAME}.{subname}")


class RankTagFilter(logging.Filter):
    """Guarantees every record has a ``rank`` attribute so the tagged
    format string never KeyErrors: sites that know their rank pass
    ``extra={"rank": r}``; everything else renders as ``-``."""

    def filter(self, record: logging.LogRecord) -> bool:
        if not hasattr(record, "rank"):
            record.rank = "-"
        return True


def basic_config(level: int = logging.INFO) -> logging.Logger:
    """Install a rank/comm-tagged stderr handler on the package logger
    (idempotent). For standalone processes — the rank daemon's __main__,
    benchmark drivers — where nobody else configures logging."""
    logger = logging.getLogger(ROOT_NAME)
    if not any(getattr(h, "_accl_tpu_tagged", False)
               for h in logger.handlers):
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(
            "[%(asctime)s accl_tpu r%(rank)s] %(levelname)s "
            "%(name)s: %(message)s"))
        handler.addFilter(RankTagFilter())
        handler._accl_tpu_tagged = True
        logger.addHandler(handler)
        logger.propagate = False  # the tagged handler owns the output
    logger.setLevel(level)
    return logger
