"""Model families exercising the framework's collectives at training scale.

The reference is a collectives library with no models; BASELINE config 5
(DP gradient all-reduce over Llama-3-8B bucketed grads) requires a real
transformer. These models are written TPU-first: pure-jax functional,
static shapes, sharding-annotated for dp/tp/sp meshes, bfloat16 compute.
"""

from .llama import LlamaConfig, Llama
from .moe import MoEConfig, MoELayer, moe_apply_sharded

__all__ = ["LlamaConfig", "Llama", "MoEConfig", "MoELayer",
           "moe_apply_sharded"]
