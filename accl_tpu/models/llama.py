"""Llama-family transformer, TPU-first (pure jax, GSPMD-sharded).

Design for the MXU/HBM/ICI (not a port of any torch code):
  * bfloat16 activations/params option, fp32 master weights + optimizer.
  * static shapes, no python control flow under jit; layers scanned.
  * GSPMD sharding: params and activations carry PartitionSpecs over a
    ('dp', 'tp') mesh (+ optional 'sp' sequence axis folded into dp for
    data, attention over tp heads). XLA inserts the all-gathers /
    reduce-scatters; bucketed DP gradient sync can instead be driven
    explicitly through accl_tpu collectives (the BASELINE config-5 path,
    benchmarks/configs.py:config5_llama_grads) to mirror the reference's
    ring-allreduce usage.

Shapes follow the Llama-3 family (GQA, SwiGLU, RoPE, RMSNorm);
``LlamaConfig.llama3_8b()`` reproduces the 8B geometry for BASELINE
config 5.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp

from ..utils.compat import shard_map as _shard_map
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14_336
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16      # activation/compute dtype (MXU-friendly)
    param_dtype: Any = jnp.float32
    # "flash": fused Pallas attention (ops.attention) — streaming KV,
    # native GQA (no repeated-KV copy), fused decode over the cache.
    # "dense": score-materializing einsum reference path. The GSPMD-
    # sharded forward uses flash too when a ``mesh`` is passed: a
    # shard_map over the tp head shards (sp None, head counts dividing
    # tp), or ring attention over the sp sequence shards (mesh + sp).
    # Sharded decode (forward_cached/generate with mesh) runs the fused
    # decode kernel per tp KV-head shard. Without a mesh, sharded paths
    # fall back to dense (a bare pallas_call has no GSPMD partitioning
    # rule).
    attention: str = "flash"
    # Mixture-of-experts FFN (Mixtral-style): n_experts > 0 replaces
    # every layer's SwiGLU with a top-k routed expert block
    # (models.moe.MoELayer math — static capacity, einsum dispatch);
    # the Switch-style load-balancing aux loss is added in loss() with
    # moe_aux_coef. 0 = dense FFN.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def tiny(cls, vocab: int = 256, dim: int = 64, n_layers: int = 2,
             n_heads: int = 4, n_kv_heads: int = 2, ffn_dim: int = 128,
             max_seq_len: int = 128) -> "LlamaConfig":
        return cls(vocab_size=vocab, dim=dim, n_layers=n_layers,
                   n_heads=n_heads, n_kv_heads=n_kv_heads, ffn_dim=ffn_dim,
                   max_seq_len=max_seq_len)


def _rms_norm(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * w


def _rope(x, positions, theta):
    """Rotary embedding; x: (..., seq, heads, head_dim)."""
    hd = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32) / hd))
    angles = positions[..., None].astype(jnp.float32) * freqs  # (seq, hd/2)
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class Llama:
    """Functional Llama: params are a pytree dict; methods are pure.

    Layer params are stacked along a leading ``n_layers`` axis so the
    decoder runs as one ``lax.scan`` — one compiled layer body regardless of
    depth (fast compiles, XLA-friendly)."""

    def __init__(self, config: LlamaConfig):
        self.config = config

    # -- parameters --------------------------------------------------------
    def init(self, key: jax.Array) -> dict:
        c = self.config
        k_emb, k_layers, k_out = jax.random.split(key, 3)
        hd, nh, nkv = c.head_dim, c.n_heads, c.n_kv_heads

        def norm_init(*shape):
            return jnp.ones(shape, c.param_dtype)

        def dense(key, fan_in, *shape):
            return (jax.random.normal(key, shape, c.param_dtype)
                    * (fan_in ** -0.5))

        L = c.n_layers
        ks = jax.random.split(k_layers, 8)

        def stack(key, fan_in, *shape):
            return dense(key, fan_in, L, *shape)

        if c.n_experts:
            # one source of truth for the expert param layout: vmap
            # MoELayer.init over the layer axis (hand-duplicating its
            # shapes here would silently diverge on any MoE change)
            ffn = jax.vmap(self._moe_layer().init)(
                jax.random.split(ks[4], L))
        else:
            ffn = {
                "w_gate": stack(ks[4], c.dim, c.dim, c.ffn_dim),
                "w_up": stack(ks[5], c.dim, c.dim, c.ffn_dim),
                "w_down": stack(ks[6], c.ffn_dim, c.ffn_dim, c.dim),
            }
        params = {
            "embed": dense(k_emb, c.dim, c.vocab_size, c.dim),
            "layers": {
                "attn_norm": norm_init(L, c.dim),
                "wq": stack(ks[0], c.dim, c.dim, nh * hd),
                "wk": stack(ks[1], c.dim, c.dim, nkv * hd),
                "wv": stack(ks[2], c.dim, c.dim, nkv * hd),
                "wo": stack(ks[3], nh * hd, nh * hd, c.dim),
                "mlp_norm": norm_init(L, c.dim),
                **ffn,
            },
            "final_norm": norm_init(c.dim),
            "lm_head": dense(k_out, c.dim, c.dim, c.vocab_size),
        }
        return params

    def _moe_layer(self):
        from .moe import MoEConfig, MoELayer
        c = self.config
        return MoELayer(MoEConfig(
            dim=c.dim, ffn_dim=c.ffn_dim, n_experts=c.n_experts,
            top_k=c.moe_top_k, capacity_factor=c.moe_capacity_factor,
            dtype=c.dtype, param_dtype=c.param_dtype))

    def _ffn(self, h, p):
        """The per-layer FFN on normed activations h (B, S, D): SwiGLU,
        or the routed expert block when n_experts > 0. Returns
        (out (B, S, D), aux scalar)."""
        c = self.config
        if not c.n_experts:
            gate = jax.nn.silu(h @ p["w_gate"].astype(h.dtype))
            up = h @ p["w_up"].astype(h.dtype)
            return (gate * up) @ p["w_down"].astype(h.dtype), jnp.zeros(
                (), jnp.float32)
        B, S, D = h.shape
        layer = self._moe_layer()
        mparams = {k: p[k] for k in ("router", "w_gate", "w_up", "w_down")}
        # route PER SEQUENCE (vmap over batch): dispatch/combine tensors
        # are O(group_tokens * E * capacity), so the group must be a
        # sequence, not the flattened global batch — at 8B-scale token
        # counts a flat group's dispatch tensor alone would not fit in
        # HBM. Expert-parallel sharding over an ep axis is the scale-out
        # form (models.moe.moe_apply_sharded).
        out, aux = jax.vmap(lambda t: layer.apply_dense(mparams, t))(h)
        return out, jnp.mean(aux)

    # -- sharding ----------------------------------------------------------
    def param_specs(self, dp: str = "dp", tp: str = "tp") -> dict:
        """PartitionSpecs for a (dp, tp) mesh: megatron-style TP — qkv/gate/
        up column-parallel, wo/down row-parallel, embeddings sharded on
        vocab. MoE expert weights (leading (L, E) axes) shard their
        per-expert matmul dims the same column/row-parallel way; the
        router is replicated."""
        if self.config.n_experts:
            ffn = {
                "router": P(None, None, None),
                "w_gate": P(None, None, None, tp),
                "w_up": P(None, None, None, tp),
                "w_down": P(None, None, tp, None),
            }
        else:
            ffn = {
                "w_gate": P(None, None, tp),
                "w_up": P(None, None, tp),
                "w_down": P(None, tp, None),
            }
        return {
            "embed": P(tp, None),
            "layers": {
                "attn_norm": P(None, None),
                "wq": P(None, None, tp),
                "wk": P(None, None, tp),
                "wv": P(None, None, tp),
                "wo": P(None, tp, None),
                "mlp_norm": P(None, None),
                **ffn,
            },
            "final_norm": P(None),
            "lm_head": P(None, tp),
        }

    def shard_params(self, params: dict, mesh: Mesh, dp: str = "dp",
                     tp: str = "tp") -> dict:
        specs = self.param_specs(dp, tp)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs,
            is_leaf=lambda x: isinstance(x, (jnp.ndarray, np.ndarray)))

    # -- forward -----------------------------------------------------------
    def _layer(self, x, layer_params, positions, mask, use_flash=False,
               shard_ctx=None):
        c = self.config
        p = layer_params
        hd, nh, nkv = c.head_dim, c.n_heads, c.n_kv_heads
        B, S, D = x.shape

        h = _rms_norm(x, p["attn_norm"].astype(x.dtype), c.norm_eps)
        q = (h @ p["wq"].astype(x.dtype)).reshape(B, S, nh, hd)
        k = (h @ p["wk"].astype(x.dtype)).reshape(B, S, nkv, hd)
        v = (h @ p["wv"].astype(x.dtype)).reshape(B, S, nkv, hd)
        q = _rope(q, positions, c.rope_theta)
        k = _rope(k, positions, c.rope_theta)
        if use_flash:
            # fused path: KV heads stay un-repeated — the kernel's index
            # maps route each Q head to its KV head (GQA without the
            # max_len-sized repeat copy); differentiable (custom VJP)
            from ..ops.attention import flash_attention
            qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
            if shard_ctx is not None:
                # GSPMD sharded attention, one shard_map either way:
                # - "tp": heads are column-parallel; attention is
                #   embarrassingly parallel across head shards, so the
                #   fused kernel runs per shard.
                # - "sp": sequence sharded over the ring; ring attention
                #   rotates the (un-repeated GQA) KV blocks over ICI
                #   while each shard's Q accumulates — the long-context
                #   schedule, no full-sequence gather ever.
                # (check_vma=False: the pallas interpreter's internal
                # slices don't carry varying-axis types, ulysses parity)
                mode, mesh, dp_ax, ax = shard_ctx
                if mode == "tp":
                    spec = P(dp_ax, ax, None, None)
                    f = functools.partial(flash_attention, causal=True)
                else:
                    from ..parallel.ring_attention import ring_attention

                    spec = P(dp_ax, None, ax, None)
                    f = functools.partial(ring_attention, axis_name=ax,
                                          causal=True)
                attn = _shard_map(f, mesh=mesh,
                                     in_specs=(spec, spec, spec),
                                     out_specs=spec,
                                     check_vma=False)(qt, kt, vt)
            else:
                attn = flash_attention(qt, kt, vt, causal=True)
            attn = attn.transpose(0, 2, 1, 3).reshape(B, S, nh * hd)
        else:
            # GQA: repeat kv heads
            rep = nh // nkv
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
            # attention (B, nh, S, hd)
            q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
            scores = jnp.einsum(
                "bhqd,bhkd->bhqk", q, k,
                preferred_element_type=jnp.float32) * (hd ** -0.5)
            scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            attn = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
            attn = attn.transpose(0, 2, 1, 3).reshape(B, S, nh * hd)
        x = x + attn @ p["wo"].astype(x.dtype)

        h = _rms_norm(x, p["mlp_norm"].astype(x.dtype), c.norm_eps)
        ffn_out, aux = self._ffn(h, p)
        x = x + ffn_out
        return x, aux

    def forward(self, params: dict, tokens: jnp.ndarray,
                dp: str | None = None, sp: str | None = None,
                mesh: Mesh | None = None, tp: str = "tp") -> jnp.ndarray:
        """Logits for (B, S) int32 tokens (see _forward_with_aux, which
        additionally returns the MoE load-balancing aux loss)."""
        return self._forward_with_aux(params, tokens, dp, sp, mesh, tp)[0]

    def _forward_with_aux(self, params: dict, tokens: jnp.ndarray,
                          dp: str | None = None, sp: str | None = None,
                          mesh: Mesh | None = None,
                          tp: str = "tp"):
        """Logits for (B, S) int32 tokens. When dp/sp axis names are given,
        activation sharding constraints pin batch->dp and seq->sp.

        With ``mesh`` also given, attention runs fused inside a
        shard_map: over the tp head shards when sp is None (requires the
        tp axis size to divide the head counts, GQA KV heads included), or
        as RING attention over the sp sequence shards when sp is given
        (un-repeated GQA KV on every hop, no full-sequence gather)."""
        c = self.config
        B, S = tokens.shape
        x = params["embed"].astype(c.dtype)[tokens]
        if dp is not None:
            x = jax.lax.with_sharding_constraint(x, P(dp, sp, None))
        positions = jnp.arange(S)
        shard_ctx = None
        if (c.attention == "flash" and dp is None and sp is None
                and mesh is None):
            # unsharded: the bare pallas_call. A passed mesh must NOT
            # land here — a bare pallas_call has no GSPMD partitioning
            # rule, so sharded operands need the shard_map tp branch.
            use_flash = True
        elif c.attention == "flash" and mesh is not None and sp is None:
            # tensor-parallel training: fused attention over the tp head
            # shards. Same loud-failure discipline as the sp branch
            # below (and as forward_cached): a silent dense fallback
            # would materialize the O(S^2) score tensor the fused path
            # exists to avoid.
            if tp not in mesh.shape:
                raise ValueError(
                    f"mesh given but tp axis {tp!r} is not in mesh "
                    f"{tuple(mesh.shape)}: name the model axis via tp=, "
                    "or omit mesh= for the unsharded fused kernel")
            if (c.n_heads % mesh.shape[tp]
                    or c.n_kv_heads % mesh.shape[tp]):
                raise ValueError(
                    f"tp axis size {mesh.shape[tp]} must divide the head "
                    f"counts (n_heads={c.n_heads}, "
                    f"n_kv_heads={c.n_kv_heads})")
            # (a dp name missing from the mesh already fails loudly at
            # the embedding's with_sharding_constraint; an INDIVISIBLE
            # batch traces through it fine and would only die later with
            # a cryptic shard_map divisibility error — catch it here)
            if dp is not None and dp in mesh.shape and B % mesh.shape[dp]:
                raise ValueError(
                    f"batch {B} not divisible by dp axis size "
                    f"{mesh.shape[dp]}")
            use_flash = True
            shard_ctx = ("tp", mesh, dp, tp)
        elif c.attention == "flash" and mesh is not None and sp is not None:
            # sequence-parallel training: ring attention over the sp
            # axis. A silent fallback to dense here would materialize
            # the O(S^2) score tensor sequence parallelism exists to
            # avoid — fail loudly when the request can't be honored.
            if sp not in mesh.shape:
                raise ValueError(f"sp axis {sp!r} not in mesh "
                                 f"{tuple(mesh.shape)}")
            if S % mesh.shape[sp]:
                raise ValueError(
                    f"sequence length {S} not divisible by sp axis size "
                    f"{mesh.shape[sp]} — ring attention needs equal "
                    "sequence shards")
            if dp is not None and dp not in mesh.shape:
                raise ValueError(f"dp axis {dp!r} not in mesh "
                                 f"{tuple(mesh.shape)}")
            if dp is not None and B % mesh.shape[dp]:
                raise ValueError(
                    f"batch {B} not divisible by dp axis size "
                    f"{mesh.shape[dp]}")
            use_flash = True
            shard_ctx = ("sp", mesh, dp, sp)
        else:
            use_flash = False
        # dense needs the materialized mask; the flash kernel masks
        # blockwise in VMEM
        mask = (None if use_flash
                else jnp.tril(jnp.ones((S, S), bool))[None, None])

        def body(x, layer_params):
            x, aux = self._layer(x, layer_params, positions, mask,
                                 use_flash, shard_ctx)
            return x, aux

        x, auxes = jax.lax.scan(body, x, params["layers"])
        x = _rms_norm(x, params["final_norm"].astype(x.dtype), c.norm_eps)
        logits = x @ params["lm_head"].astype(c.dtype)
        return logits.astype(jnp.float32), jnp.sum(auxes)

    # -- inference: KV-cache decode ----------------------------------------
    def init_kv_cache(self, batch: int, max_len: int, dtype=None) -> dict:
        """Preallocated static-shape KV cache: (L, B, max_len, n_kv, hd)
        per tensor + a scalar fill position. Static shapes keep every
        decode step a single compiled program (no growing arrays)."""
        c = self.config
        dt = dtype or c.dtype
        shape = (c.n_layers, batch, max_len, c.n_kv_heads, c.head_dim)
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt),
                "pos": jnp.zeros((), jnp.int32)}

    def _layer_cached(self, x, layer_params, kc, vc, pos,
                      shard_ctx=None):
        """One decoder layer over cached context: x holds S_new tokens at
        absolute positions pos..pos+S_new-1; kc/vc are (B, max_len, nkv, hd)
        and are updated in place (dynamic_update_slice). Returns
        (x, kc, vc)."""
        c = self.config
        p = layer_params
        hd, nh, nkv = c.head_dim, c.n_heads, c.n_kv_heads
        B, S, D = x.shape
        max_len = kc.shape[1]

        h = _rms_norm(x, p["attn_norm"].astype(x.dtype), c.norm_eps)
        positions = pos + jnp.arange(S)
        q = (h @ p["wq"].astype(x.dtype)).reshape(B, S, nh, hd)
        k = (h @ p["wk"].astype(x.dtype)).reshape(B, S, nkv, hd)
        v = (h @ p["wv"].astype(x.dtype)).reshape(B, S, nkv, hd)
        q = _rope(q, positions, c.rope_theta)
        k = _rope(k, positions, c.rope_theta)
        kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype),
                                          (0, pos, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                          (0, pos, 0, 0))

        if self.config.attention == "flash":
            # fused decode kernel over the cache's native layout: cache
            # blocks past the fill (pos + S) are neither fetched nor
            # computed, so a step costs the filled prefix, not max_len
            from ..ops.attention import flash_decode
            qt = q.transpose(0, 2, 1, 3)
            if shard_ctx is not None:
                # tp decode: KV-head shards of the cache stay put; each
                # tp shard decodes its own head group with the fused
                # kernel (no cache gather, no repeated-KV copy)
                mesh, dp_ax, tp_ax = shard_ctx
                attn = _shard_map(
                    flash_decode,
                    mesh=mesh,
                    in_specs=(P(dp_ax, tp_ax, None, None),
                              P(dp_ax, None, tp_ax, None),
                              P(dp_ax, None, tp_ax, None), P()),
                    out_specs=P(dp_ax, tp_ax, None, None),
                    check_vma=False)(qt, kc, vc, pos + S)
            else:
                attn = flash_decode(qt, kc, vc, kv_len=pos + S)
            attn = attn.transpose(0, 2, 1, 3).reshape(B, S, nh * hd)
        else:
            # grouped-query attention without materializing repeated K/V
            # over max_len (that copy is the cost GQA exists to avoid):
            # fold the per-kv-head query group into the einsum instead
            rep = nh // nkv
            qg = q.reshape(B, S, nkv, rep, hd)        # (B, S, nkv, rep, hd)
            kt = kc.astype(x.dtype)                   # (B, max, nkv, hd)
            vt = vc.astype(x.dtype)
            scores = jnp.einsum(
                "bskrd,btkd->bkrst", qg, kt,
                preferred_element_type=jnp.float32) * (hd ** -0.5)
            kpos = jnp.arange(max_len)
            mask = kpos[None, :] <= positions[:, None]  # (S, max) causal
            scores = jnp.where(mask[None, None, None], scores,
                               jnp.finfo(jnp.float32).min)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            attn = jnp.einsum("bkrst,btkd->bskrd", probs, vt)
            attn = attn.reshape(B, S, nh * hd)
        x = x + attn @ p["wo"].astype(x.dtype)

        h = _rms_norm(x, p["mlp_norm"].astype(x.dtype), c.norm_eps)
        ffn_out, _aux = self._ffn(h, p)  # aux is a training-time signal
        x = x + ffn_out
        return x, kc, vc

    def forward_cached(self, params: dict, tokens: jnp.ndarray,
                       cache: dict, mesh: Mesh | None = None,
                       dp: str | None = None,
                       tp: str = "tp") -> tuple[jnp.ndarray, dict]:
        """Logits for S_new tokens appended at cache['pos'], plus the
        updated cache. Used for both prefill (S_new = prompt len) and
        decode (S_new = 1); jit once per S_new. With ``mesh`` given (and
        the tp axis size dividing the head counts), decode attention
        runs the fused kernel per tp KV-head shard — tensor-parallel
        inference without gathering the cache."""
        c = self.config
        x = params["embed"].astype(c.dtype)[tokens]
        pos = cache["pos"]
        shard_ctx = None
        if mesh is not None:
            # fail loudly (sp-path discipline): a silent fallback would
            # trace the bare pallas decode over sharded globals and XLA
            # would all-gather the ENTIRE cache to every device per step
            if c.attention != "flash":
                raise ValueError("mesh-sharded decode requires "
                                 "attention='flash'")
            if tp not in mesh.shape:
                raise ValueError(f"tp axis {tp!r} not in mesh "
                                 f"{tuple(mesh.shape)}")
            if c.n_heads % mesh.shape[tp] or c.n_kv_heads % mesh.shape[tp]:
                raise ValueError(
                    f"tp axis size {mesh.shape[tp]} must divide the "
                    f"head counts ({c.n_heads} q / {c.n_kv_heads} kv) "
                    "for sharded decode")
            if dp is not None and dp not in mesh.shape:
                raise ValueError(f"dp axis {dp!r} not in mesh "
                                 f"{tuple(mesh.shape)}")
            if dp is not None and tokens.shape[0] % mesh.shape[dp]:
                raise ValueError(
                    f"batch {tokens.shape[0]} not divisible by dp axis "
                    f"size {mesh.shape[dp]}")
            shard_ctx = (mesh, dp, tp)

        def body(xc, layer):
            x = xc
            lp, kc, vc = layer
            x, kc, vc = self._layer_cached(x, lp, kc, vc, pos, shard_ctx)
            return x, (kc, vc)

        x, (knew, vnew) = jax.lax.scan(
            body, x, (params["layers"], cache["k"], cache["v"]))
        x = _rms_norm(x, params["final_norm"].astype(x.dtype), c.norm_eps)
        logits = (x @ params["lm_head"].astype(c.dtype)).astype(jnp.float32)
        new_cache = {"k": knew, "v": vnew,
                     "pos": pos + tokens.shape[1]}
        return logits, new_cache

    def generate(self, params: dict, prompt: jnp.ndarray, max_new: int,
                 max_len: int | None = None,
                 temperature: float = 0.0,
                 key: jax.Array | None = None,
                 mesh: Mesh | None = None,
                 dp: str | None = None, tp: str = "tp") -> jnp.ndarray:
        """Greedy (or temperature) decode: prefill the prompt, then one
        jitted single-token step per new token. Returns (B, max_new)."""
        B, S = prompt.shape
        max_len = max_len or (S + max_new)
        # the last sampled token is never stepped, so S + max_new - 1 cache
        # slots are written; a short cache would silently clamp
        # dynamic_update_slice and corrupt attention instead of erroring
        if max_len < S + max_new - 1:
            raise ValueError(
                f"max_len={max_len} too small for prompt {S} + "
                f"{max_new - 1} cached decode steps")
        cache = self.init_kv_cache(B, max_len)
        # one cached jit serves prefill and decode (distinct trace-cache
        # entries per S_new); rebuilding wrappers per call would recompile
        step = self._jit_forward_cached()
        if mesh is not None:
            step = functools.partial(step, mesh=mesh, dp=dp, tp=tp)
        logits, cache = step(params, prompt, cache)
        out = []
        last = logits[:, -1]
        if key is None:
            key = jax.random.key(0)
        for i in range(max_new):
            if temperature > 0:
                key, sub = jax.random.split(key)
                tok = jax.random.categorical(sub, last / temperature, axis=-1)
            else:
                tok = jnp.argmax(last, axis=-1)
            out.append(tok)
            if i + 1 < max_new:  # the last sampled token needs no step
                logits, cache = step(params, tok[:, None], cache)
                last = logits[:, -1]
        return jnp.stack(out, axis=1)

    def _jit_forward_cached(self):
        fn = getattr(self, "_fc_jit", None)
        if fn is None:
            fn = jax.jit(self.forward_cached,
                         static_argnames=("mesh", "dp", "tp"))
            self._fc_jit = fn
        return fn

    def loss(self, params: dict, tokens: jnp.ndarray,
             dp: str | None = None, sp: str | None = None,
             mesh: Mesh | None = None, tp: str = "tp") -> jnp.ndarray:
        """Next-token cross entropy (mean over B, S-1), plus the MoE
        load-balancing aux term scaled by moe_aux_coef when experts are
        enabled."""
        logits, aux = self._forward_with_aux(params, tokens, dp, sp,
                                             mesh, tp)
        logits = logits[:, :-1]
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = jnp.mean(-jnp.take_along_axis(logp, targets[..., None],
                                            axis=-1))
        if self.config.n_experts:
            nll = nll + self.config.moe_aux_coef * aux
        return nll

    # -- training ----------------------------------------------------------
    def make_train_step(self, optimizer, dp: str | None = None,
                        sp: str | None = None,
                        mesh: Mesh | None = None, tp: str = "tp"):
        """Returns train_step(params, opt_state, tokens) -> (params,
        opt_state, loss). Pure; jit/pjit outside. Pass ``mesh`` to run
        attention as the fused flash kernel over tp head shards."""

        def train_step(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(self.loss)(
                params, tokens, dp, sp, mesh, tp)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss

        return train_step

    def param_count(self, params: dict) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))

    def grad_buckets(self, params: dict, bucket_bytes: int = 25 << 20
                     ) -> list[list[str]]:
        """Group parameter leaves into ~bucket_bytes buckets (DDP-style
        bucketed gradient all-reduce; BASELINE config 5). Returns lists of
        pytree key-paths, in reverse layer order like bucketed DDP."""
        leaves = jax.tree_util.tree_leaves_with_path(params)
        buckets, cur, cur_bytes = [], [], 0
        for path, leaf in reversed(leaves):
            key = jax.tree_util.keystr(path)
            nbytes = int(np.prod(leaf.shape)) * 4
            cur.append(key)
            cur_bytes += nbytes
            if cur_bytes >= bucket_bytes:
                buckets.append(cur)
                cur, cur_bytes = [], 0
        if cur:
            buckets.append(cur)
        return buckets
