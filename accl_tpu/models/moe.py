"""Mixture-of-Experts layer with expert parallelism over a mesh axis.

TPU-first design: top-k routing with a static per-rank capacity (so every
shape is fixed under jit — dropped tokens are the standard price for a
compiled dispatch), einsum-built dispatch/combine tensors (MXU-friendly,
no scatters), and ONE ``lax.all_to_all`` each way over the ``ep`` axis —
the same alltoall the ACCL surface exposes as a collective
(accl.alltoall / moveengine.expand_alltoall).

The expert FFN is the Llama SwiGLU block with a leading experts axis,
sharded over ``ep`` so each rank computes only its resident experts.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp

from ..utils.compat import axis_size as _axis_size
from ..utils.compat import shard_map as _shard_map
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    dim: int = 64
    ffn_dim: int = 128
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    def capacity(self, tokens: int) -> int:
        """Static per-rank expert capacity for a token count."""
        return max(1, int(np.ceil(
            tokens * self.top_k * self.capacity_factor / self.n_experts)))


class MoELayer:
    """Functional MoE FFN: router + E SwiGLU experts."""

    def __init__(self, config: MoEConfig):
        self.config = config

    def init(self, key: jax.Array) -> dict:
        c = self.config
        kr, kg, ku, kd = jax.random.split(key, 4)
        E, d, f = c.n_experts, c.dim, c.ffn_dim

        def dense(key, fan_in, *shape):
            return (jax.random.normal(key, shape, c.param_dtype)
                    * (fan_in ** -0.5))

        return {
            "router": dense(kr, d, d, E),
            "w_gate": dense(kg, d, E, d, f),
            "w_up": dense(ku, d, E, d, f),
            "w_down": dense(kd, f, E, f, d),
        }

    def param_specs(self, ep: str = "ep") -> dict:
        return {"router": P(None, None), "w_gate": P(ep, None, None),
                "w_up": P(ep, None, None), "w_down": P(ep, None, None)}

    # -- routing -----------------------------------------------------------
    def _route(self, params: dict, x: jnp.ndarray, capacity: int):
        """Build dispatch/combine tensors for tokens x: (T, d).

        Returns (dispatch (T, E, C) bool-ish, combine (T, E, C) float,
        aux_loss scalar)."""
        c = self.config
        E, k = c.n_experts, c.top_k
        logits = x.astype(jnp.float32) @ params["router"].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)          # (T, E)
        vals, idx = lax.top_k(probs, k)                   # (T, k)
        sel = jax.nn.one_hot(idx, E, dtype=jnp.float32)   # (T, k, E)
        mask = jnp.sum(sel, axis=1)                       # (T, E) in {0,1}
        gates = mask * probs / jnp.maximum(
            jnp.sum(vals, axis=-1, keepdims=True), 1e-9)  # renormalized
        # position of each token in its expert's queue (first-come order)
        pos = jnp.cumsum(mask, axis=0) - mask             # (T, E)
        keep = (pos < capacity) * mask
        dispatch = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                  dtype=jnp.float32) * keep[..., None]
        combine = dispatch * gates[..., None]
        # load-balancing aux loss (Switch-style): E * mean_frac_tokens .
        # mean_frac_probs
        frac_tokens = jnp.mean(mask, axis=0)
        frac_probs = jnp.mean(probs, axis=0)
        aux = E * jnp.sum(frac_tokens * frac_probs) / c.top_k
        return dispatch, combine, aux

    def _expert_ffn(self, params: dict, t: jnp.ndarray) -> jnp.ndarray:
        """t: (E_local, N, d) -> (E_local, N, d), SwiGLU per expert."""
        c = self.config
        wg = params["w_gate"].astype(c.dtype)
        wu = params["w_up"].astype(c.dtype)
        wd = params["w_down"].astype(c.dtype)
        t = t.astype(c.dtype)
        gate = jax.nn.silu(jnp.einsum("end,edf->enf", t, wg))
        up = jnp.einsum("end,edf->enf", t, wu)
        return jnp.einsum("enf,efd->end", gate * up, wd)

    # -- single-device reference ------------------------------------------
    def apply_dense(self, params: dict, x: jnp.ndarray,
                    capacity: int | None = None):
        """All experts local (the EP path must match this exactly when
        nothing exceeds capacity). x: (T, d)."""
        C = capacity or self.config.capacity(x.shape[0])
        dispatch, combine, aux = self._route(params, x, C)
        expert_in = jnp.einsum("tec,td->ecd", dispatch,
                               x.astype(jnp.float32))
        expert_out = self._expert_ffn(params, expert_in)
        out = jnp.einsum("tec,ecd->td", combine,
                         expert_out.astype(jnp.float32))
        return out.astype(x.dtype), aux

    # -- expert-parallel path ---------------------------------------------
    def apply_ep(self, params_local: dict, x: jnp.ndarray, axis_name: str,
                 capacity: int | None = None):
        """Inside shard_map: tokens sharded over ``axis_name`` (T_local, d);
        expert params carry only this rank's E/W experts."""
        c = self.config
        W = _axis_size(axis_name)
        E = c.n_experts
        assert E % W == 0, f"{E} experts not divisible by ep={W}"
        E_loc = E // W
        C = capacity or c.capacity(x.shape[0])
        dispatch, combine, aux = self._route(params_local, x, C)
        # local dispatch (E, C, d) -> (W, E_loc, C, d) -> alltoall so each
        # rank receives every rank's slice for ITS experts
        expert_in = jnp.einsum("tec,td->ecd", dispatch,
                               x.astype(jnp.float32))
        expert_in = expert_in.reshape(W, E_loc, C, -1)
        expert_in = lax.all_to_all(expert_in, axis_name, split_axis=0,
                                   concat_axis=0, tiled=False)
        # (W, E_loc, C, d): axis 0 = originating rank; fold into tokens
        t = expert_in.transpose(1, 0, 2, 3).reshape(E_loc, W * C, -1)
        out = self._expert_ffn(params_local, t)
        out = out.reshape(E_loc, W, C, -1).transpose(1, 0, 2, 3)
        out = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                             tiled=False)                  # back home
        out = out.reshape(E, C, -1)
        y = jnp.einsum("tec,ecd->td", combine, out.astype(jnp.float32))
        return y.astype(x.dtype), aux


@functools.lru_cache(maxsize=None)
def _ep_program(cfg: MoEConfig, mesh: Mesh, axis_name: str, capacity: int):
    lyr = MoELayer(cfg)
    pspec = lyr.param_specs(axis_name)
    xspec = P(axis_name, None)

    @functools.partial(_shard_map, mesh=mesh,
                       in_specs=(pspec, xspec), out_specs=(xspec, P()))
    def f(params, x):
        out, aux = lyr.apply_ep(params, x, axis_name, capacity)
        return out, lax.pmean(aux, axis_name)

    return jax.jit(f)


def moe_apply_sharded(layer: MoELayer, params: dict, x: jax.Array,
                      mesh: Mesh, axis_name: str = "ep",
                      capacity: int | None = None):
    """Global-array entry: x (T, d) token-sharded over ``axis_name``;
    expert params sharded on their leading axis. Returns (out, aux)."""
    W = mesh.shape[axis_name]
    C = capacity or layer.config.capacity(x.shape[0] // W)
    specs = layer.param_specs(axis_name)
    placed = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in params.items()}
    xs = jax.device_put(x, NamedSharding(mesh, P(axis_name, None)))
    prog = _ep_program(layer.config, mesh, axis_name, C)
    return prog(placed, xs)
