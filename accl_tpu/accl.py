"""The ACCL driver: the user-facing host API.

Method-for-method capability parity with the reference's canonical PYNQ
driver class (driver/pynq/accl.py:293-985): buffer management, communicator
and arithmetic configuration, the full primitive/collective surface
(``nop/send/recv/copy/combine/bcast/scatter/gather/reduce/allgather/
allreduce/reduce_scatter``), sync/async call forms with ``waitfor=``
chaining, error decode, and introspection dumps. Extensions the TPU build
adds as first-class: ``barrier``, ``alltoall``, algorithm selectors, and
mesh-backed execution (device/tpu.py).

Buffers are uncompressed/compressed pairs exactly like the reference's
``prepare_call`` dtype resolution: a call may mix at most two dtypes, the
narrower of which is the "compressed" form, with per-operand compression
flags computed automatically (accl.py:528-592).
"""

from __future__ import annotations

import contextlib
import itertools
from typing import Any, Sequence

import numpy as np

from .arith import DEFAULT_ARITH_CONFIGS, resolve_arith_config
from .buffer import ACCLBuffer
from .call import CallDescriptor, CallHandle, CompletedHandle
from .communicator import Communicator
from .constants import (ACCLError, CCLOp, CfgFunc, CollectiveAlgorithm,
                        Compression, DEFAULT_ALGORITHMS,
                        DEFAULT_MAX_SEGMENT_SIZE, ErrorCode,
                        HIERARCHICAL_OPS, ReduceFunc, StreamFlags, TAG_ANY,
                        VALID_ALGORITHMS)
from .device.base import Device
from .log import get_logger
from .retry import RetryPolicy, resolve_policy
from .tracing import METRICS, Profiler, TRACE

log = get_logger(__name__)


def _phases_strip_flat(compress_phases: str | None) -> bool:
    """Validate a per-phase compression selector and answer whether a
    FLAT (non-hierarchical) execution should drop the wire compression:
    "inter" compresses only inter-host hierarchy phases, and a flat call
    has none — EQuARX semantics, where intra-host traffic always stays
    full precision."""
    if compress_phases in (None, "all"):
        return False
    if compress_phases == "inter":
        return True
    raise ValueError(
        f"compress_phases must be None, 'all' or 'inter', got "
        f"{compress_phases!r}")


class ACCL:
    """One rank's handle to the collective engine.

    Args:
        device: the execution backend (EmuDevice / SimDevice / TpuDevice).
        comm: the world communicator for this rank.
        timeout: receive timeout in seconds (set_timeout parity).
        max_segment_size: wire segmentation granularity. When None, the
            attached tuner recommends one against the backend's
            ``preferred_segment_size()`` (no tuner: the preferred size).
        tuner: optional :class:`~accl_tpu.tuner.Tuner` resolving AUTO
            algorithm selectors by size/topology and learning from
            retire-time measurements. Multi-rank worlds must share ONE
            tuner instance across their ranks (all member ranks of a
            collective must agree on the algorithm).
        tenant: optional multi-tenant service label (accl_tpu/service):
            every communicator this driver registers is grouped under it
            for admission scheduling, resource quotas and per-tenant
            metrics/trace attribution. Default: each communicator is its
            own tenant.
    """

    def __init__(self, device: Device, comm: Communicator,
                 timeout: float = 30.0,
                 max_segment_size: int | None = None,
                 arith_registry=None, tuner=None,
                 tenant: str | None = None,
                 retry_policy: "RetryPolicy | None" = None,
                 verify_integrity: bool = False):
        self.device = device
        # Tier-2 integrity (PR 13): verify replicated-result collectives
        # (allreduce / allgather / bcast) by fingerprinting the result
        # buffer (crc32 — cheap, and exact because the engines hold
        # results bit-identical across ranks) and cross-checking the
        # fingerprints in a small follow-up allgather. Catches what
        # retransmission cannot: LOCAL combine/scratch/memory corruption
        # that lands a wrong result with a clean wire. A mismatch raises
        # typed DATA_INTEGRITY_ERROR naming the disagreeing rank(s) —
        # never blind-retried (retry.py). Must be UNIFORM across the
        # ranks of a communicator (the exchange is itself a collective),
        # like retry policies. Sync calls only; per-call
        # ``verify_integrity=`` overrides either way.
        self.verify_integrity = bool(verify_integrity)
        # driver-wide default retry policy (accl_tpu/retry.py): applied
        # to every data call unless a per-call retries=/retry_policy=
        # overrides it. Must be UNIFORM across the ranks of a
        # communicator, like the collectives themselves.
        self.retry_policy = retry_policy
        self._preflight_warned: set = set()
        if tenant is not None:
            from .service import validate_tenant
            validate_tenant(tenant)  # label is spliced into CSV/metrics/
            # trace encodings — reject unsafe charsets at the API edge
        self.tenant = tenant
        self._arith_memo: dict[frozenset, object] = {}
        self.arith_registry = (arith_registry if arith_registry is not None
                               else dict(DEFAULT_ARITH_CONFIGS))
        self.communicators: list[Communicator] = []
        # per-global-rank address book: the most recently registered
        # Rank record for each global rank this driver has ever seen
        # (grow_communicator's member-record resolution source — see
        # _register_comm for why the comm registry's order is not
        # recency)
        self._rank_book: dict[int, "Rank"] = {}
        self._barrier_buf: ACCLBuffer | None = None
        self._scratch_bufs: dict[tuple[int, str], ACCLBuffer] = {}
        self.profiler = Profiler()
        self.tuner = tuner
        # two-tier hierarchy (accl_tpu/hier): configured explicitly via
        # configure_hierarchy() or auto-derived once from a tuner's
        # MeshTopology on the first AUTO-resolved collective
        self._hier = None
        self._hier_autoprobe = True
        # logical-call attribution: phases of a hierarchical/redistribute
        # program record this tag as CallRecord.parent (one driver is
        # used from one thread at a time — the established driver
        # threading contract)
        self._parent_tag = ""
        # redistribution engine state: memoized plans (pure geometry),
        # cached member-subset sub-communicators, and recycled async
        # staging buffers (popped at issue by the driver thread,
        # appended back by the completion callback — GIL-atomic ops)
        self._redist_plans: dict = {}
        self._redist_comms: dict = {}
        self._redist_stage_pool: dict = {}
        self._redist_seq = itertools.count(1)
        # one-sided RMA windows (accl_tpu/rma): ids handed out from a
        # per-driver counter, so symmetric registration order yields
        # agreeing ids across ranks without a handshake — the same
        # determinism contract split_communicator uses for comm ids
        self._next_window = itertools.count(1)
        self._windows: dict[int, ACCLBuffer] = {}
        # async calls this driver has issued that have not retired yet —
        # tuner-training measurements only happen on a quiet device
        # (an unrelated in-flight call would add its queue wait to the
        # measured window)
        import threading as _threading
        self._async_mu = _threading.Lock()
        self._async_inflight = 0
        # per-communicator call/byte accounting (QoS attribution
        # foundation, ROADMAP item 3). Kept as plain driver-local dicts —
        # the per-call hot path is GIL-cheap dict arithmetic, no
        # process-wide lock — and folded into the registry by a WEAK
        # collector only when someone snapshots. (op, comm_id) -> n.
        self._call_counts: dict[tuple, int] = {}
        self._byte_counts: dict[tuple, int] = {}
        METRICS.register_collector(self, ACCL._metrics_rows)
        if tuner is not None:
            if tuner.topology is None:
                tuner.topology = device.topology()
            # engine-level AUTO resolution for descriptors that reach the
            # move engine still unresolved (moveengine.expand_call)
            device.tuner = tuner
            # tuner re-resolution (refresh/pin — the points where
            # epsilon-greedy or EWMA switching can flip a decision) must
            # invalidate the device's compiled-plan cache: a switched
            # algorithm lands on a new key, and stale entries for the
            # old choice are dropped rather than accumulated
            cache = getattr(device, "plan_cache", None)
            if cache is not None:
                tuner.register_plan_cache(cache)
            # fleet-shared tuning table (tuner/cache.py env override):
            # pins load best-effort — a missing/stale cache is not an
            # error — and once per tuner, not once per rank sharing it
            from .tuner import cache as _tcache
            if (_tcache.default_cache_path()
                    and not getattr(tuner, "_env_cache_loaded", False)):
                tuner._env_cache_loaded = True
                try:
                    _tcache.load_into(tuner)
                except (OSError, ValueError):
                    pass
        device.configure_communicator(comm, tenant=tenant)
        self._register_comm(comm)
        # bring-up sequence through the call path, mirroring the reference
        # driver init: set_timeout, enable_pkt, set_max_segment_size
        # (accl.py:374-380 <-> ccl_offload_control.c:1248-1279)
        self.set_timeout(timeout)
        self._config_call(CfgFunc.enable_pkt, 1)
        if max_segment_size is None:
            max_segment_size = device.preferred_segment_size()
            if tuner is not None:
                max_segment_size = tuner.recommend_segment_size(
                    max_segment_size)
        self.set_max_segment_size(max_segment_size)

    def _scratch(self, count: int, dtype) -> ACCLBuffer:
        """Reusable internal scratch buffer (e.g. gather relay)."""
        key = (count, np.dtype(dtype).name)
        if key not in self._scratch_bufs:
            self._scratch_bufs[key] = self.buffer((count,), dtype)
        return self._scratch_bufs[key]

    # -- lifecycle ---------------------------------------------------------
    @property
    def arith_registry(self) -> dict:
        """Arithmetic-config registry. Rebinding it invalidates the
        resolution memo; for IN-PLACE mutation call
        :meth:`invalidate_arith_cache` afterwards."""
        return self._arith_registry

    @arith_registry.setter
    def arith_registry(self, registry: dict):
        self._arith_registry = registry
        self._arith_memo.clear()

    def invalidate_arith_cache(self):
        """Drop memoized arith-config resolutions (call after mutating
        ``arith_registry`` in place)."""
        self._arith_memo.clear()

    @property
    def comm(self) -> Communicator:
        return self.communicators[0]

    @property
    def rank(self) -> int:
        return self.comm.local_rank

    @property
    def world_size(self) -> int:
        return self.comm.size

    def _config_call(self, fn: CfgFunc, value: int, comm_id: int = 0):
        """Issue an ACCL_CONFIG call through the full call path: the
        backend — not just the host — sees and applies the subfunction
        (reference: case ACCL_CONFIG, ccl_offload_control.c:1240-1283).
        Subfunction rides in ``tag``, value in ``count``."""
        self._call(CallDescriptor(CCLOp.config, count=int(value),
                                  comm_id=comm_id, tag=int(fn)),
                   run_async=False, waitfor=())

    def set_timeout(self, timeout: float):
        self._config_call(CfgFunc.set_timeout, int(round(timeout * 1000)))
        # client-side wait-budget bookkeeping (the SimDevice poll loop and
        # the in-process workers keep their own copy of the deadline)
        self.device.timeout = timeout

    def set_max_segment_size(self, nbytes: int):
        self._config_call(CfgFunc.set_max_segment_size, int(nbytes))

    def open_port(self):
        """Verify/arm the fabric listener (openPort parity, c:168-181)."""
        self._config_call(CfgFunc.open_port, 0)

    def init_connection(self, comm: Communicator | None = None):
        """Eagerly open sessions to every peer of ``comm`` (reference
        init_connection = open_port + open_con, accl.py driver bring-up;
        openCon c:109-165). Without it, the socket fabric dials lazily on
        first send — this pre-establishes, like the reference's TCP stack.
        """
        comm = comm or self.comm
        self._config_call(CfgFunc.open_port, 0, comm_id=comm.comm_id)
        self._config_call(CfgFunc.open_con, 0, comm_id=comm.comm_id)

    def close_connections(self):
        self._config_call(CfgFunc.close_con, 0)

    def set_stack_type(self, stack: str):
        """Runtime transport-stack select (HOUSEKEEP_SET_STACK_TYPE parity,
        c:1270-1272): 'tcp' or 'udp'. Every rank must switch while the
        fabric is quiesced."""
        code = {"tcp": 0, "udp": 1}[stack]
        self._config_call(CfgFunc.set_stack_type, code)

    def split_communicator(self, members: Sequence[int],
                           key: int = 0) -> Communicator:
        """Create and register a sub-communicator of world ranks ``members``.

        All member ranks must call this with the same ``members`` (the
        comm_id is derived deterministically from the membership, so members
        agree without a handshake; pass distinct ``key`` values to create
        multiple communicators over the same member set).
        """
        sub = self.comm.split(members, key=key)
        # splits inherit the driver's tenant grouping: a tenant's data-
        # parallel replicas and its sub-groups schedule/quota as ONE
        # tenant (accl_tpu/service)
        self.device.configure_communicator(sub, tenant=self.tenant)
        self._register_comm(sub)
        return sub

    # -- failure containment (ULFM-style revoke/shrink) --------------------
    def revoke(self, comm: Communicator | None = None):
        """Mark a communicator revoked: every later call on it raises
        ``PEER_FAILED`` immediately instead of rendezvousing with ranks
        that may be dead, and async handles ALREADY in flight on it
        abort with the typed error now (``device.abort_comm``) instead
        of riding out their full receive deadline. The application then
        rebuilds on the survivors via :meth:`shrink_communicator`.
        Rank-local (like the failure observation itself) — every
        surviving rank revokes when it observes
        ``ErrorCode.PEER_FAILED``; other communicators keep flowing
        untouched."""
        comm = comm or self.comm
        comm.revoked = True
        self.device.abort_comm(comm.comm_id, int(ErrorCode.PEER_FAILED))

    def shrink_communicator(self, dead_ranks: Sequence[int],
                            comm: Communicator | None = None,
                            key: int = 0x5A1D) -> Communicator:
        """Build and register the survivor communicator of ``comm``
        minus ``dead_ranks`` (GLOBAL ranks). Every surviving rank must
        call this with the same ``dead_ranks`` (the new comm_id derives
        deterministically from the survivor membership, like
        :meth:`split_communicator`); the dead ranks' channel state never
        carries over — the shrunken comm has fresh sequence spaces."""
        comm = comm or self.comm
        dead = {int(d) for d in dead_ranks}
        if comm.my_global_rank in dead:
            raise ValueError("cannot shrink away the local rank")
        survivors = [i for i, r in enumerate(comm.ranks)
                     if r.global_rank not in dead]
        if len(survivors) == len(comm.ranks):
            raise ValueError(f"no member of comm {comm.comm_id} is in "
                             f"dead_ranks {sorted(dead)}")
        sub = comm.split(survivors, key=key)
        self.device.configure_communicator(sub, tenant=self.tenant)
        self._register_comm(sub)
        METRICS.inc("membership_shrink_total", rank=self.rank)
        if TRACE.enabled:
            TRACE.emit("membership_shrink", rank=self.rank,
                       nbytes=len(survivors), peer=-1)
        return sub

    def grow_communicator(self, new_ranks: Sequence,
                          comm: Communicator | None = None,
                          base_members: Sequence[int] | None = None,
                          key: int = 0,
                          handshake_timeout: float | None = None,
                          retries: int | None = None,
                          retry_policy: "RetryPolicy | None" = None
                          ) -> Communicator:
        """Build, register, and bootstrap the grown communicator of
        ``comm`` plus ``new_ranks`` — the dual of
        :meth:`shrink_communicator`, and the recovery half of the
        elastic-membership story (the failure half is heartbeat
        detection + revoke + shrink).

        Every member of the NEW communicator — survivors and joiners —
        must call this with the same membership (SPMD, like every
        membership operation). Survivors pass their current (shrunken)
        communicator as ``comm``; a JOINER, which is not a member of
        that comm, instead passes ``base_members`` (the GLOBAL ranks of
        the communicator it is joining). ``new_ranks`` entries are
        global rank ints (addresses resolved from any registered
        communicator — the world comm knows everyone) or explicit
        :class:`~accl_tpu.communicator.Rank` records for ranks this
        driver has never seen.

        The grown membership is ordered by global rank and its comm_id
        derives deterministically from (membership, key), so all members
        agree without negotiation. When the grown membership+key matches
        an existing communicator (the canonical grow-back-to-the-world
        after a shrink), registration is a RE-configuration riding the
        existing epoch machinery: the device bumps its comm epoch (so no
        compiled plan of the old membership survives), the fabric drops
        the comm's retransmission channel state, and every member's seqn
        spaces restart at zero — stale ring/retx state is invalidated,
        never inherited.

        After configuring, a bootstrap JOIN handshake runs: every member
        announces itself (strm=JOIN hello frames carrying the membership
        signature) and waits for every peer, so no member can issue a
        collective on the grown comm before all members exist and agree
        — and a joiner that died (or never started) surfaces as a typed
        ``JOIN_FAILED`` instead of a first-collective deadline. The
        handshake is a retryable phase (``retries=``/``retry_policy=``,
        driver default otherwise): a slow joiner gets fresh attempts
        with the policy's uniform backoff. On final failure the grown
        comm is revoked (later calls on it refuse typed) and the error
        raises."""
        import time as _time
        if base_members is not None:
            if comm is not None:
                raise ValueError(
                    "pass either comm= or base_members=, not both (a "
                    "joiner names the membership it joins with "
                    "base_members; members pass their communicator)")
            base = [int(g) for g in base_members]
        else:
            comm = comm or self.comm
            base = [r.global_rank for r in comm.ranks]
        from .communicator import Rank, grown_communicator
        explicit: dict[int, Rank] = {}
        new_globals: list[int] = []
        for entry in new_ranks:
            if isinstance(entry, Rank):
                explicit[entry.global_rank] = entry
                new_globals.append(entry.global_rank)
            else:
                new_globals.append(int(entry))
        members = sorted(set(base) | set(new_globals))
        joiners = sorted(set(new_globals) - set(base))
        me = self.comm.my_global_rank
        if me not in members:
            raise ValueError(
                f"local rank (global {me}) is not a member of the grown "
                f"communicator {members} — joiners list themselves in "
                f"new_ranks or base_members")
        if not joiners:
            raise ValueError(
                f"nothing to grow: {sorted(set(new_globals))} are all "
                f"members of the base {sorted(set(base))} already")
        records = []
        for g in members:
            # explicit Rank records win; otherwise the driver's address
            # book — updated on EVERY registration, so the most recently
            # learned (host, port) for a global rank is authoritative
            # regardless of where its comm sits in the registry (a
            # reversed scan of self.communicators is NOT recency:
            # _register_comm replaces same-id comms in place, so a fresh
            # re-addressed record can live at an EARLIER index than a
            # stale one)
            rec = explicit.get(g) or self._rank_book.get(g)
            records.append(rec if rec is not None
                           else Rank(global_rank=g))
        grown = grown_communicator(records, me,
                                   mesh_axis=self.comm.mesh_axis,
                                   key=key)
        # register FIRST (riding the reconfiguration epoch machinery),
        # THEN handshake: each member sends its hello only after its own
        # seqn spaces and plan-cache epoch are fresh, so a peer that
        # completes the handshake and immediately issues a collective
        # can never race a member still carrying old-membership state
        self.device.configure_communicator(grown, tenant=self.tenant)
        policy = resolve_policy(retries, retry_policy, self.retry_policy)
        timeout = (handshake_timeout if handshake_timeout is not None
                   else getattr(self.device, "timeout", 5.0))
        attempt = 0
        while True:
            err = int(self.device.join_handshake(grown, timeout))
            if not err:
                break
            if policy is not None and policy.should_retry(err, attempt):
                METRICS.inc("membership_join_retries_total",
                            rank=self.rank)
                log.warning(
                    "rank %d: join handshake for grown comm %d failed "
                    "(0x%x) — retry %d", self.rank, grown.comm_id, err,
                    attempt + 1, extra={"rank": self.rank})
                _time.sleep(policy.backoff(attempt, grown.comm_id))
                attempt += 1
                continue
            grown.revoked = True
            METRICS.inc("membership_join_fail_total", rank=self.rank)
            raise ACCLError(err, f"grow_communicator{members}")
        self._register_comm(grown)
        METRICS.inc("membership_grow_total", rank=self.rank,
                    joiners=len(joiners))
        if TRACE.enabled:
            TRACE.emit("membership_grow", rank=self.rank,
                       nbytes=len(members), peer=-1)
        return grown

    def _register_comm(self, comm: Communicator):
        """Track a (re)built communicator, REPLACING any registered comm
        of the same id: after a grow-back the old-membership object (and
        its revoked flag) must not shadow the fresh one in comm_of().
        Every registration also refreshes the driver's per-global-rank
        address book — the recency source grow_communicator resolves
        member records from (list position is not recency: replacement
        happens in place)."""
        for r in comm.ranks:
            if r.global_rank >= 0:
                self._rank_book[r.global_rank] = r
        for i, c in enumerate(self.communicators):
            if c.comm_id == comm.comm_id:
                self.communicators[i] = comm
                return
        self.communicators.append(comm)

    def preflight(self, count: int, dtype=np.float32,
                  op: str = "allreduce",
                  comm: Communicator | None = None) -> list[str]:
        """Resource preflight for a planned collective: returns human-
        readable warnings (empty = clear). Today's one check is the
        PR-8 known issue: a hierarchical lowering of a multi-MiB call
        parks phase chunks in the finite rx pool, and ``nbufs*bufsize``
        below ~2 chunks degrades into timeout-shaped backpressure —
        surfaced here (and logged once per shape at hierarchical
        issue time) instead of discovered as a mystery deadline."""
        comm = comm or self.comm
        nbytes = int(count) * np.dtype(dtype).itemsize
        if comm is not self.comm or op not in HIERARCHICAL_OPS:
            return []
        return self._preflight_hier(op, nbytes)

    def _preflight_hier(self, op: str, nbytes: int) -> list[str]:
        """Price the FULL N-tier phase chain against the rx pool: each
        boundary tier's exchange parks blocks of roughly
        ``nbytes / groups(tier)`` in the finite pool, so coarser tiers
        (fewer groups, bigger blocks) are the first to breach the
        2-chunk rule — the warning names the offending tier."""
        cap_fn = getattr(self.device, "rx_capacity", None)
        hier = self._hier
        if cap_fn is None or hier is None:
            return []
        try:
            nbufs, bufsize = cap_fn()
        except Exception:  # noqa: BLE001 — preflight must never break
            return []      # the call it is trying to protect
        pool_bytes = nbufs * bufsize
        nest = getattr(hier, "nest", None) or (hier.groups,)
        warnings = []
        for k, grouping in enumerate(nest):
            n_groups = max(1, len(grouping))
            chunk = -(-nbytes // n_groups)
            if pool_bytes >= 2 * chunk:
                continue
            tier = "inter" if k == 0 else f"inter{k + 1}"
            unit = "hosts" if k == 0 else "groups"
            warnings.append(
                f"rx pool ({nbufs} x {bufsize} B = {pool_bytes} B) cannot "
                f"hold 2 chunks ({2 * chunk} B) of a hierarchical {op} of "
                f"{nbytes} B on tier {tier} ({n_groups} {unit}): expect "
                f"timeout-shaped backpressure — raise nbufs/bufsize or "
                f"split the call")
        return warnings

    # -- N-tier hierarchy (accl_tpu/hier) ----------------------------------
    def configure_hierarchy(self, hosts: Sequence[int],
                            levels: Sequence[Sequence[int]] = ()):
        """Declare the world's tier structure: ``hosts[r]`` is the host
        id of world rank ``r`` (each host's ranks contiguous), and each
        entry of ``levels`` adds one coarser boundary (rack, pod, ...)
        as another rank->group-id map, innermost-first. Builds the
        per-tier sub-communicators the HIERARCHICAL phase programs run
        over; every rank must configure the same mapping (sub-comm ids
        derive deterministically from membership, like
        :meth:`split_communicator`). Returns the
        :class:`~accl_tpu.hier.Hierarchy`."""
        from .hier import Hierarchy
        self._hier = Hierarchy(self, hosts, levels=levels)
        return self._hier

    @property
    def hierarchy(self):
        return self._hier

    def _ensure_hier(self):
        """Auto-configure the hierarchy once from an attached tuner's
        MeshTopology (the emu ``hosts=``/``outer_tiers=`` wiring and
        real deployments both land here) — deterministic across ranks,
        since every rank binds the same device topology. A mesh with
        coarser ``outer`` boundaries configures the full N-tier nest."""
        if self._hier is not None or not self._hier_autoprobe:
            return self._hier
        self._hier_autoprobe = False
        topo = getattr(self.tuner, "topology", None)
        groups = getattr(topo, "groups", None)
        if groups and len(groups) > 1 \
                and sum(len(g) for g in groups) == self.comm.size:
            from .hier import Hierarchy
            levels_fn = getattr(topo, "hosts_levels", None)
            if callable(levels_fn) and getattr(topo, "outer", ()):
                maps = levels_fn()
                self._hier = Hierarchy(self, maps[0], levels=maps[1:])
            else:
                self._hier = Hierarchy(self, topo.hosts_list())
        return self._hier

    def _hier_route(self, op: str, comm: Communicator, count: int,
                    elem_bytes: int, algorithm) -> bool:
        """True when this collective must lower to a hierarchical phase
        program instead of a flat descriptor. Explicit HIERARCHICAL
        demands a configured hierarchy over the world communicator;
        AUTO routes when the shared tuner's two-tier cost model says
        the phase program beats every flat schedule."""
        if isinstance(algorithm, str):
            algorithm = CollectiveAlgorithm[algorithm.upper()]
        alg = CollectiveAlgorithm(algorithm)
        H = CollectiveAlgorithm.HIERARCHICAL
        if alg == H:
            if self._ensure_hier() is None:
                raise ValueError(
                    "HIERARCHICAL requires a configured hierarchy: call "
                    "configure_hierarchy(hosts) on every rank (or attach "
                    "a tuner whose topology is a MeshTopology)")
            if comm is not self.comm:
                raise ValueError(
                    "hierarchical collectives run over the WORLD "
                    "communicator (the hierarchy's sub-communicators are "
                    "derived from it); got a split communicator")
            self._warn_preflight(op, count * elem_bytes)
            return True
        if (alg != CollectiveAlgorithm.AUTO or self.tuner is None
                or comm is not self.comm or op not in HIERARCHICAL_OPS):
            return False
        if self._parent_tag:
            # already inside a logical program (a redistribute's
            # internal allgather/alltoall, a hierarchy phase): stay
            # flat — nested hierarchical lowering would overwrite the
            # parent attribution tag and re-chain phases under a
            # different logical call
            return False
        if self._ensure_hier() is None:
            return False
        routed = self.tuner.select(op, comm.size,
                                   count * elem_bytes) == H
        if routed:
            self._warn_preflight(op, count * elem_bytes)
        return routed

    def _warn_preflight(self, op: str, nbytes: int):
        """Log the rx-pool preflight warnings once per (op, size) shape
        at hierarchical issue time (ACCL.preflight is the query form)."""
        key = (op, nbytes)
        if key in self._preflight_warned:
            return
        self._preflight_warned.add(key)
        for w in self._preflight_hier(op, nbytes):
            log.warning("rank %d preflight: %s", self.rank, w,
                        extra={"rank": self.rank})

    @contextlib.contextmanager
    def _retry_scope(self, retries, retry_policy):
        """Per-call ``retries=``/``retry_policy=`` for COMPOSITE calls
        (redistribute, hierarchical lowerings): their sub-calls are
        issued internally, so the per-call override becomes the driver
        default for the issuing scope (one driver is used from one
        thread at a time — the established driver threading contract)."""
        policy = resolve_policy(retries, retry_policy, self.retry_policy)
        prev = self.retry_policy
        self.retry_policy = policy
        try:
            yield
        finally:
            self.retry_policy = prev

    @contextlib.contextmanager
    def _attributed(self, tag: str):
        """Scope marking every call issued inside it as a phase of one
        logical call: their CallRecords carry ``parent=tag``."""
        prev = self._parent_tag
        self._parent_tag = tag
        try:
            yield
        finally:
            self._parent_tag = prev

    def soft_reset(self):
        """Rank-local soft reset through the call path (HOUSEKEEP_SWRST
        parity, c:1244-1247): drains the rx pool and zeroes seqnos."""
        self._config_call(CfgFunc.reset_periph, 0)

    # -- profiling (parity: start/end_profiling cfg calls,
    #    xlnx-consts.hpp:27-28; SURVEY §5 tracing subsystem) ----------------
    def start_profiling(self):
        """Enable per-call timing capture. Issues the config call through
        the full call path (backends arm their own counters — the socket
        daemons' profiled-call counts are visible via get_info), then arms
        the host-side recorder."""
        self._config_call(CfgFunc.start_profiling, 0)
        self.profiler.start()

    def end_profiling(self):
        self._config_call(CfgFunc.end_profiling, 0)
        self.profiler.stop()

    # -- observability (SURVEY §5: the ILA-probe/waveform-dump analogs) ----
    def start_trace(self):
        """Arm the process-wide flight recorder
        (:data:`~accl_tpu.tracing.TRACE`): the streamed executor, egress
        stage, combine workers, RX pools and fabrics start emitting
        structured stage events into per-thread ring buffers. Also armed
        by ``ACCL_TPU_TRACE=1``. Near-free for everyone else: disarmed
        emit sites are a single attribute test."""
        TRACE.start()

    def stop_trace(self):
        TRACE.stop()

    def export_trace(self, path: str) -> int:
        """Write the flight recorder's current ring as Chrome/Perfetto
        trace-event JSON (open in chrome://tracing or ui.perfetto.dev;
        one track per lane/worker per rank). Returns the event count."""
        return TRACE.export_chrome(path)

    def metrics_snapshot(self) -> dict:
        """One process-wide health surface: every counter/gauge/histogram
        of :data:`~accl_tpu.tracing.METRICS` — per-call accounting
        (labeled op/comm_id), fabric counters (per communicator), RX-pool
        occupancy, executor pipeline gauges, plan-cache counters, daemon
        ingress rejections, tuner exploration picks — merged with every
        live registered collector's rows."""
        return METRICS.snapshot()

    def metrics_text(self) -> str:
        """Prometheus-style text exposition of :meth:`metrics_snapshot`
        (scrape-ready for the multi-tenant service story, ROADMAP 3)."""
        return METRICS.to_prometheus()

    def _metrics_rows(self):
        """Registry-collector rows for this driver's per-communicator
        call accounting (polled at snapshot time only). ``rank`` keeps
        one world's drivers apart; ``ctx`` (the emu fabric's instance
        tag, when the backend has one) keeps concurrently live same-shape
        worlds apart — their membership-CRC comm_ids collide."""
        labels = {"rank": self.rank}
        fab = getattr(getattr(self.device, "ctx", None), "fabric", None)
        ctx_seq = getattr(fab, "ctx_seq", None)
        if ctx_seq is not None:
            labels["ctx"] = ctx_seq
        # tenant attribution on the driver rows (PR 11): serving traffic
        # (put/get) is separable from collectives per tenant straight
        # from the exposition, without joining against CallRecords
        for (op, comm_id), n in list(self._call_counts.items()):
            yield ("counter", "accl_calls_total",
                   dict(labels, op=op, comm_id=comm_id,
                        tenant=self.tenant or f"comm-{comm_id}"), n)
        for (op, comm_id), n in list(self._byte_counts.items()):
            yield ("counter", "accl_bytes_total",
                   dict(labels, op=op, comm_id=comm_id,
                        tenant=self.tenant or f"comm-{comm_id}"), n)

    def deinit(self):
        # withdraw THIS driver's windows only — on a shared device
        # (multi-tenant) other tenants' registrations must survive
        for wid in list(self._windows):
            try:
                self.deregister_window(wid)
            except Exception:
                pass
        self.device.deinit()

    # -- buffers -----------------------------------------------------------
    def buffer(self, shape=None, dtype=np.float32, data=None,
               device_resident: bool = False) -> ACCLBuffer:
        """Allocate a device-registered buffer (reference: accl.buffer /
        pynq allocate).

        Pass a live ``jax.Array`` as ``data`` (or ``device_resident=True``
        with shape/dtype) for a device-resident buffer: TPU-backend calls
        then skip host staging entirely — the reference's
        ``to_from_fpga=False`` fast path. Backends without device arrays
        reject the request."""
        from .buffer import _is_jax_array
        if data is not None and _is_jax_array(data):
            data = self.device.adopt_device_array(data)
        elif device_resident:
            if data is not None:
                shape, dtype = np.shape(data), np.asarray(data).dtype
            data = self.device.make_device_array(shape, dtype, data)
        elif data is not None:
            data = np.ascontiguousarray(data)
            shape = data.shape
            dtype = data.dtype
        return ACCLBuffer(shape, dtype=dtype, device=self.device, data=data)

    # -- call plumbing -----------------------------------------------------
    def _resolve_wire(self, op: str, comm: Communicator, count: int,
                      operand_dtype, compress_dtype, block_scale):
        """Resolve ``compress_dtype="auto"``: the tuner prices the
        quantized wire variant (beta scaled by the wire-byte ratio plus
        the quant/dequant gamma term, tuner/cost.py) against the
        full-precision one and picks per (op, world, size) — fp8-e4m3
        block-scaled wire exactly in the bandwidth-bound band, no
        compression for latency-bound calls. Opt-in by the literal
        "auto": AUTO algorithm selection alone never changes numerics,
        and "auto" on a non-f32 call quietly stays uncompressed (the
        block-scaled lane is f32-only — crashing a call that runs fine
        uncompressed would make "auto" unsafe to sprinkle)."""
        if block_scale and compress_dtype is None:
            # the flat path raises this from _prepare; raising HERE too
            # keeps hierarchical lowerings (which never reach _prepare
            # with the caller's kwargs) from silently dropping the ask
            raise ValueError(
                "block_scale needs a compress_dtype naming the quantized "
                "wire dtype (int8 / float8_e4m3fn / float8_e5m2)")
        if not (isinstance(compress_dtype, str)
                and compress_dtype == "auto"):
            return compress_dtype, block_scale
        dt = None if operand_dtype is None else np.dtype(operand_dtype)
        if dt == np.dtype(np.float32) and self.tuner is not None \
                and self.tuner.select_wire(op, comm.size,
                                           count * dt.itemsize):
            import ml_dtypes
            return np.dtype(ml_dtypes.float8_e4m3fn), \
                (block_scale if block_scale else True)
        return None, False

    def _quant_block_for(self, count: int, elem_bytes: int,
                         block_scale) -> int:
        """The call's scale-block size: an explicit int is clamped into
        the legal envelope; ``True`` asks the tuner (falling back to the
        default) — larger blocks amortize the scale header, smaller ones
        track local dynamic range."""
        from . import quant
        if block_scale is True:
            if self.tuner is not None:
                return self.tuner.recommend_quant_block(
                    count * elem_bytes)
            return quant.DEFAULT_BLOCK
        return quant.clamp_block(int(block_scale))

    def _prepare(self, scenario: CCLOp, *, count: int, comm: Communicator,
                 root_src_dst: int = 0, func: ReduceFunc = ReduceFunc.SUM,
                 tag: int = TAG_ANY,
                 op0: ACCLBuffer | None = None, op1: ACCLBuffer | None = None,
                 res: ACCLBuffer | None = None,
                 compress_dtype: np.dtype | str | None = None,
                 block_scale: bool | int = False,
                 stream_dtype: np.dtype | str | None = None,
                 stream_flags: StreamFlags = StreamFlags.NO_STREAM,
                 algorithm: CollectiveAlgorithm | str = (
                     CollectiveAlgorithm.AUTO)
                 ) -> CallDescriptor:
        """Resolve dtypes to an arith config + compression flags.

        Parity: prepare_call (accl.py:528-592) — collect operand dtypes,
        find the matching arithmetic config, mark each narrower-typed
        operand OP{0,1}/RES_COMPRESSED, and request ETH_COMPRESSED when the
        caller asks for wire compression. ``block_scale`` (with
        ``compress_dtype``) upgrades the wire from plain narrowing to
        block-scaled quantization (accl_tpu/quant.py): True = tuner-
        recommended block size, an int = explicit block.
        """
        if getattr(comm, "revoked", False):
            # ULFM-style containment: a revoked communicator accepts no
            # further calls — the application shrinks to the survivors
            # (shrink_communicator) and rebuilds there
            raise ACCLError(int(ErrorCode.PEER_FAILED),
                            f"{scenario.name} on revoked communicator "
                            f"{comm.comm_id}")
        dtypes = {b.dtype for b in (op0, op1, res) if b is not None}
        compression = Compression.NONE
        if stream_dtype is not None:
            # streamed operands carry no buffer to resolve a dtype from —
            # without this a fully-streamed call silently coerces to f32
            dtypes.add(np.dtype(stream_dtype))
        if compress_dtype is not None:
            dtypes.add(np.dtype(compress_dtype))
            compression |= Compression.ETH_COMPRESSED
            if block_scale:
                compression |= Compression.BLOCK_SCALED
        elif block_scale:
            raise ValueError(
                "block_scale needs a compress_dtype naming the quantized "
                "wire dtype (int8 / float8_e4m3fn / float8_e5m2)")
        if not dtypes:
            dtypes = {np.dtype(np.float32)}
        # memoized: resolution walks name-sorted registry keys (~15us),
        # pure in its inputs, and on the per-call hot path. Rebinding
        # arith_registry clears the memo (property setter); in-place
        # registry mutation must call invalidate_arith_cache().
        # np.dtype hashes/compares in C — the dtype set is its own key.
        mk = frozenset(dtypes)
        cfg = self._arith_memo.get(mk)
        if cfg is None:
            cfg = resolve_arith_config(dtypes, self.arith_registry)
            self._arith_memo[mk] = cfg
        if compression & Compression.BLOCK_SCALED:
            # derive the block-scaled config (quant_block > 0 drives the
            # scale-header segmentation reserve + the executor's fused
            # dequant->accumulate->requant lane); memoized per (dtype
            # set, block) like the plain configs
            import dataclasses as _dc
            qblock = self._quant_block_for(
                count, cfg.uncompressed_elem_bytes, block_scale)
            bk = (mk, qblock)
            bcfg = self._arith_memo.get(bk)
            if bcfg is None:
                bcfg = _dc.replace(cfg, quant_block=qblock)
                self._arith_memo[bk] = bcfg
            cfg = bcfg
        elif (compression & Compression.ETH_COMPRESSED
                and cfg.is_compressing
                and cfg.compressed_dtype.kind in "iu"
                and cfg.uncompressed_dtype.kind == "f"):
            # fail at the call site, not deep in expansion: the
            # (float, int8) pair exists FOR the block-scaled lane —
            # plain astype narrowing truncates/wraps floats silently
            raise ValueError(
                f"compress_dtype={cfg.compressed_dtype.name} on "
                f"{cfg.uncompressed_dtype.name} operands requires "
                f"block-scaled quantization (pass block_scale=): plain "
                f"dtype narrowing to an integer wire would truncate")
        if cfg.is_compressing:
            if op0 is not None and op0.dtype == cfg.compressed_dtype:
                compression |= Compression.OP0_COMPRESSED
            if op1 is not None and op1.dtype == cfg.compressed_dtype:
                compression |= Compression.OP1_COMPRESSED
            if res is not None and res.dtype == cfg.compressed_dtype:
                compression |= Compression.RES_COMPRESSED
        if isinstance(algorithm, str):
            algorithm = CollectiveAlgorithm[algorithm.upper()]
        algorithm = CollectiveAlgorithm(algorithm)
        if (algorithm == CollectiveAlgorithm.AUTO and self.tuner is not None
                and scenario.name in VALID_ALGORITHMS):
            # resolve AUTO here so the concrete choice crosses the wire to
            # daemon/TPU tiers too (the engine-level fallback in
            # expand_call only covers in-process descriptors) — except for
            # ops the backend keeps for its own AUTO handling (the TPU
            # tier's 2D-tree rooted collectives, device.auto_resolvable_ops)
            resolvable = self.device.auto_resolvable_ops()
            if resolvable is None or scenario.name in resolvable:
                algorithm = self.tuner.select(
                    scenario.name, comm.size,
                    count * cfg.uncompressed_elem_bytes)
                if algorithm == CollectiveAlgorithm.HIERARCHICAL:
                    # safety net for paths that do not intercept the
                    # hierarchical route (barrier's internal allreduce,
                    # hierarchy phase calls): a flat descriptor carries
                    # a flat algorithm (accl_tpu/hier lowers
                    # HIERARCHICAL before a descriptor exists)
                    algorithm = DEFAULT_ALGORITHMS[scenario.name]
        return CallDescriptor(
            scenario=scenario, count=count, comm_id=comm.comm_id,
            root_src_dst=root_src_dst, function=func, tag=tag,
            arithcfg=cfg, compression=compression, stream_flags=stream_flags,
            algorithm=CollectiveAlgorithm(algorithm),
            addr_0=op0.address if op0 is not None else 0,
            addr_1=op1.address if op1 is not None else 0,
            addr_2=res.address if res is not None else 0)

    def _call(self, desc: CallDescriptor, run_async: bool,
              waitfor: Sequence[CallHandle], chain: bool = False,
              retries: int | None = None,
              retry_policy: "RetryPolicy | None" = None) -> CallHandle:
        """Issue a call, applying the resolved retry policy (per-call
        ``retries=``/``retry_policy=`` over the driver default). A retry
        is an epoch-scoped idempotent re-execution: the failed attempt
        advanced every per-peer seqn counter to its final value at
        admission, so the re-execution's frames live in a fresh seqn
        range stale traffic cannot satisfy; ``device.prepare_retry``
        purges the dead attempt's stranded rx frames; and the plan cache
        makes re-expansion free. Policies must be uniform across the
        ranks of a communicator (docs/ARCHITECTURE.md, Failure model)."""
        policy = resolve_policy(retries, retry_policy, self.retry_policy)
        if (policy is None or policy.retries <= 0
                or desc.scenario == CCLOp.config):
            return self._call_once(desc, run_async, waitfor, chain)
        if run_async:
            return self._call_async_retry(desc, waitfor, chain, policy)
        import time as _time
        attempt = 0
        while True:
            try:
                return self._call_once(desc, run_async, waitfor, chain)
            except ACCLError as exc:
                if policy.should_retry(exc.error_word, attempt):
                    self._note_retry(desc, attempt, exc.error_word)
                    _time.sleep(policy.backoff(attempt, desc.comm_id))
                    attempt += 1
                    continue
                if attempt and policy.should_retry(exc.error_word, 0):
                    # retryable failure class, attempts exhausted: say so
                    raise ACCLError(
                        exc.error_word
                        | int(ErrorCode.CALL_RETRIES_EXHAUSTED),
                        desc.scenario.name) from exc
                raise

    def _note_retry(self, desc: CallDescriptor, attempt: int, word: int):
        METRICS.inc("call_retries_total", op=desc.scenario.name,
                    comm_id=desc.comm_id, rank=self.rank)
        if TRACE.enabled:
            TRACE.emit("call_retry", rank=self.rank, seqn=attempt,
                       nbytes=desc.count, peer=-1)
        log.warning(
            "rank %d: %s on comm %d failed (0x%x) — retry %d (fresh "
            "seqn epoch)", self.rank, desc.scenario.name, desc.comm_id,
            word, attempt + 1, extra={"rank": self.rank})
        prep = getattr(self.device, "prepare_retry", None)
        if prep is not None:
            try:
                prep(desc.comm_id)
            except Exception:  # noqa: BLE001 — cleanup is best-effort;
                pass           # the retry itself decides success

    def _call_async_retry(self, desc: CallDescriptor, waitfor,
                          chain: bool, policy: "RetryPolicy"
                          ) -> CallHandle:
        """Async form of the retry loop: the outer handle completes only
        when an attempt succeeds or the policy gives up; re-issues run
        off a timer thread (never on the backend's finish worker, whose
        sleep would stall other tenants' retirements)."""
        outer = CallHandle(context=desc.scenario.name)
        state = {"attempt": 0}

        def issue():
            try:
                inner = self._call_once(desc, True, waitfor, chain)
            except ACCLError as exc:
                # preserve the true error word: callers branch on it
                # (PEER_FAILED -> shrink, retryable -> their own backoff)
                outer.complete(exc.error_word, exception=exc)
                return
            except Exception as exc:  # noqa: BLE001 — surface, not hang
                outer.complete(int(ErrorCode.INVALID_CALL), exception=exc)
                return
            inner.add_done_callback(
                lambda err, h=inner: on_done(err, h))

        def on_done(err, inner):
            err = int(err)
            if err and policy.should_retry(err, state["attempt"]):
                a = state["attempt"]
                state["attempt"] = a + 1
                self._note_retry(desc, a, err)
                import threading as _threading
                t = _threading.Timer(policy.backoff(a, desc.comm_id),
                                     issue)
                t.daemon = True
                t.start()
                return
            if err and state["attempt"] and policy.should_retry(err, 0):
                err |= int(ErrorCode.CALL_RETRIES_EXHAUSTED)
            outer.complete(err, exception=inner._exception)

        issue()
        return outer

    def _call_once(self, desc: CallDescriptor, run_async: bool,
                   waitfor: Sequence[CallHandle],
                   chain: bool = False) -> CallHandle:
        import time as _time
        if chain and run_async:
            # cross-call pipelining hint (the C++ driver's call_chain
            # analog): the backend may admit this call's move program
            # while the predecessor drains — see CallDescriptor.chain
            desc.chain = True
        profiling = self.profiler.enabled and desc.scenario != CCLOp.config
        tunable = (desc.scenario.name in VALID_ALGORITHMS
                   and desc.algorithm != CollectiveAlgorithm.AUTO)
        # only unchained SYNCHRONOUS calls on a QUIET device train the
        # tuner: chained calls include predecessor wait time in their
        # issue->retire window, async calls queue behind each other on
        # the device worker, and a sync call issued while async work is
        # still in flight queues behind it too — any of these would
        # credit pipeline context, not algorithm speed, to the EWMA (the
        # Profiler keeps recording them all — attribution wants the full
        # window; training does not). Quiescence is checked across EVERY
        # driver sharing this tuner (tuner.quiescent()), not just this
        # one: multi-tenant worlds share one tuner, and another tenant's
        # concurrent storm inflating this call's window must not
        # cross-contaminate the EWMA stream.
        observing = (self.tuner is not None and tunable
                     and not run_async and not waitfor
                     and self._async_inflight == 0
                     and self.tuner.quiescent())
        t0 = _time.perf_counter() if (profiling or observing) else 0.0
        if run_async:
            # count the async call in flight BEFORE it launches: from the
            # moment call_async returns (or even mid-submission, on the
            # driver-bypass path) the storm is executing, and a sibling
            # driver checking tuner.quiescent() in that window must not
            # train on a wall clock this call is already inflating
            with self._async_mu:
                self._async_inflight += 1
            if self.tuner is not None:
                self.tuner.note_async_issue()
        try:
            handle = self.device.call_async(desc, waitfor,
                                            inline_ok=not run_async)
        except BaseException:
            if run_async:
                with self._async_mu:
                    self._async_inflight -= 1
                if self.tuner is not None:
                    self.tuner.note_async_retire()
            raise
        ebytes = (desc.arithcfg.uncompressed_elem_bytes
                  if desc.arithcfg is not None else 0)
        op = desc.scenario.name
        if desc.scenario != CCLOp.config:
            # per-communicator attribution: driver-local counters (see
            # __init__) — a registry lock here measurably skewed the
            # small-message algorithm ladder under 8 rank threads
            key = (op, desc.comm_id)
            self._call_counts[key] = self._call_counts.get(key, 0) + 1
            nb = desc.count * ebytes
            if nb:
                self._byte_counts[key] = \
                    self._byte_counts.get(key, 0) + nb
        if profiling:
            if tunable:
                alg_label = desc.algorithm.name
            elif op in VALID_ALGORITHMS:
                # AUTO descriptor: when the backend resolves every op's
                # AUTO through the shared engine path (emu/sim tiers),
                # the concrete default it will expand is knowable here —
                # record it so untuned-run history stays usable for
                # Tuner.ingest_records. Backends with internal AUTO
                # handling the enum cannot name (TPU 2D trees) get the
                # honest "AUTO" label instead.
                from .constants import DEFAULT_ALGORITHMS
                alg_label = (DEFAULT_ALGORITHMS[op].name
                             if (self.tuner is None and
                                 self.device.auto_resolvable_ops() is None)
                             else "AUTO")
            else:
                alg_label = ""
            self.profiler.attach(handle, op=op, count=desc.count,
                                 nbytes=desc.count * ebytes,
                                 comm_id=desc.comm_id, t0=t0,
                                 algorithm=alg_label,
                                 tenant=self.tenant
                                 or f"comm-{desc.comm_id}",
                                 parent=self._parent_tag)
        if observing:
            # retire-time measurement back to the tuner (same done-callback
            # path the profiler records through: async chains credit their
            # true issue->retire duration, not host dispatch time)
            tuner, op = self.tuner, desc.scenario.name
            world, nbytes = self.comm_of(desc.comm_id).size, \
                desc.count * ebytes
            alg = desc.algorithm
            quantized = bool(desc.compression & Compression.BLOCK_SCALED)

            def _feed(error_word: int, _t0=t0):
                dt = _time.perf_counter() - _t0
                tuner.observe(op, world, nbytes, alg, dt, error_word)
                # wire-variant refinement: measured quantized/plain
                # durations sharpen select_wire's cost-model crossover
                # (benchmarks/tune.py sweeps both legs deliberately)
                tuner.observe_wire(op, world, nbytes, quantized, dt,
                                   error_word)

            handle.add_done_callback(_feed)
        if run_async:
            # (in-flight counters were bumped BEFORE call_async above —
            # cross-driver visibility via tuner.quiescent() must cover
            # the launch window itself)
            comm_id = desc.comm_id

            def _retired(err):
                with self._async_mu:
                    self._async_inflight -= 1
                if self.tuner is not None:
                    self.tuner.note_async_retire()
                if err:
                    METRICS.inc("accl_call_errors_total", op=op,
                                comm_id=comm_id)

            handle.add_done_callback(_retired)
            return handle
        try:
            handle.wait()
        except ACCLError:
            METRICS.inc("accl_call_errors_total", op=op,
                        comm_id=desc.comm_id)
            raise
        return CompletedHandle(context=desc.scenario.name)

    def comm_of(self, comm_id: int) -> Communicator:
        """Registered communicator by id (world or split)."""
        for c in self.communicators:
            if c.comm_id == comm_id:
                return c
        raise KeyError(f"no communicator with id {comm_id}")

    # -- tier-2 integrity: cross-rank result fingerprinting ----------------
    def _want_verify(self, explicit: bool | None, run_async: bool,
                     compressing: bool = False) -> bool:
        """Per-call ``verify_integrity=`` over the driver default. Sync
        calls only: verification is a follow-up collective issued from
        the calling thread — an explicit request on an async call is an
        error (silently skipping it would fake coverage), the driver
        default just doesn't apply there. Wire-compressed calls are
        likewise excluded: lossy dtype narrowing legitimately
        desynchronizes result BYTES across roles (a bcast root keeps
        its original-precision buffer while receivers hold the
        narrowed-then-widened values), so a byte fingerprint would
        raise a false DATA_INTEGRITY_ERROR on a perfectly healthy
        wire — the driver default skips them, an explicit request
        raises."""
        if explicit is None and self._parent_tag:
            # phases of a hierarchical/redistribute lowering: the
            # LOGICAL call verifies its final result once — per-phase
            # exchanges would multiply the cost without adding coverage
            return False
        want = self.verify_integrity if explicit is None else bool(explicit)
        if not want:
            return False
        if compressing:
            if explicit:
                raise ValueError(
                    "verify_integrity cannot cover a compress_dtype "
                    "call: lossy wire narrowing makes result bytes "
                    "legitimately differ across ranks (the root/owner "
                    "keeps original precision), so a fingerprint "
                    "mismatch would not mean corruption")
            return False
        if run_async:
            if explicit:
                raise ValueError(
                    "verify_integrity requires a synchronous call (the "
                    "fingerprint exchange is a follow-up collective on "
                    "the calling thread); wait the handle and verify "
                    "via a sync call, or use the driver-wide default")
            return False
        return True

    def fingerprint_of(self, buf: ACCLBuffer, nelems: int | None = None
                       ) -> int:
        """Cheap content fingerprint of a result buffer: crc32 over the
        first ``nelems`` elements' raw bytes. Exact across ranks because
        the execution engines hold collective results BIT-identical (the
        differential-test invariant) — equal data, equal fingerprint."""
        import zlib
        flat = np.ascontiguousarray(buf.data).reshape(-1)
        if nelems is not None:
            flat = flat[:nelems]
        return zlib.crc32(flat.view(np.uint8)) & 0xFFFFFFFF

    def _verify_result(self, op: str, buf: ACCLBuffer, nelems: int,
                       comm: Communicator):
        """The tier-2 cross-check: allgather every rank's result
        fingerprint (one int64 — the exchange rides the now-self-healing
        wire like any small collective) and compare. A disagreement
        means some rank's RESULT bytes differ — local combine/scratch/
        memory corruption, the class neither retransmission nor the wire
        checksum can see — and raises typed DATA_INTEGRITY_ERROR naming
        the minority rank(s)."""
        fp = self.fingerprint_of(buf, nelems)
        W = comm.size
        src = self._scratch(1, np.int64)
        dst = self._scratch(W, np.int64)
        src.data[0] = fp
        self.allgather(src, dst, 1, comm=comm, verify_integrity=False)
        fps = dst.data[:W].copy()
        if TRACE.enabled:
            TRACE.emit("fingerprint", rank=self.rank, seqn=comm.comm_id,
                       peer=-1, nbytes=int(fp))
        if (fps == fp).all():
            METRICS.inc("integrity_verified_total", op=op,
                        comm_id=comm.comm_id, rank=self.rank)
            return
        vals, counts = np.unique(fps, return_counts=True)
        if counts.max() * 2 > W:
            majority = vals[counts.argmax()]
            bad = [r for r in range(W) if fps[r] != majority]
            what = f"rank(s) {bad} disagree"
        else:
            # no STRICT majority (always the case at W=2, or an even
            # split): attributing the corruption to either side would
            # be a coin flip that steers an operator at the wrong host
            # half the time — name every rank and say so
            bad = list(range(W))
            what = (f"no majority fingerprint — the split is "
                    f"undecidable, any of rank(s) {bad} may hold the "
                    f"corrupt result")
        METRICS.inc("integrity_mismatch_total", op=op,
                    comm_id=comm.comm_id, rank=self.rank)
        log.error(
            "rank %d: %s result fingerprint mismatch on comm %d — "
            "%s (fingerprints %s). Local data "
            "corruption: NOT retried (a re-execution could mask it).",
            self.rank, op, comm.comm_id, what, [int(f) for f in fps],
            extra={"rank": self.rank})
        raise ACCLError(
            int(ErrorCode.DATA_INTEGRITY_ERROR),
            f"{op} on comm {comm.comm_id}: result fingerprint "
            f"mismatch — {what}")

    # -- primitives (parity: accl.py:738-985) ------------------------------
    def nop(self, run_async: bool = False, chain: bool = False,
            waitfor: Sequence[CallHandle] = (),
            retries: int | None = None,
            retry_policy: "RetryPolicy | None" = None
            ) -> CallHandle:
        """No-op through the full call path; used for call-latency probes
        (accl.py:738-745)."""
        return self._call(CallDescriptor(CCLOp.nop), run_async, waitfor,
                          chain, retries, retry_policy)

    def copy(self, srcbuf: ACCLBuffer | None, dstbuf: ACCLBuffer | None,
             count: int | None = None, *,
             comm: Communicator | None = None,
             stream_flags: StreamFlags = StreamFlags.NO_STREAM,
             stream_dtype=None, run_async: bool = False, chain: bool = False,
             waitfor: Sequence[CallHandle] = (),
             retries: int | None = None,
             retry_policy: "RetryPolicy | None" = None
             ) -> CallHandle:
        """Local copy. With OP0_STREAM the source is the rank's stream-in
        port (srcbuf may be None); with RES_STREAM the result goes to the
        stream-out port (dstbuf may be None) — the external-kernel data
        paths (reference: SWITCH_M_BYPASS / loopback plugin). A fully
        streamed copy takes its element type from ``stream_dtype``
        (default float32). ``comm`` scopes attribution/ordering only —
        no bytes cross the wire — and matters when the default comm is
        revoked: a reshard's local slice copies ride the EXCHANGE
        communicator, so elastic recovery works while the world comm is
        down (the whole point of revoke + shrink)."""
        if count is None:
            if srcbuf is not None:
                count = srcbuf.size
            elif dstbuf is not None:
                count = dstbuf.size
            else:
                raise ValueError("copy with both operands streamed "
                                 "requires an explicit count")
        desc = self._prepare(CCLOp.copy, count=count,
                             comm=comm or self.comm,
                             op0=srcbuf, res=dstbuf,
                             stream_dtype=stream_dtype,
                             stream_flags=stream_flags)
        return self._call(desc, run_async, waitfor, chain,
                          retries, retry_policy)

    def combine(self, count: int, func: ReduceFunc, op0: ACCLBuffer | None,
                op1: ACCLBuffer, res: ACCLBuffer | None, *,
                stream_dtype=None,
                stream_flags: StreamFlags = StreamFlags.NO_STREAM,
                run_async: bool = False, chain: bool = False,
                waitfor: Sequence[CallHandle] = (),
                retries: int | None = None,
                retry_policy: "RetryPolicy | None" = None
                ) -> CallHandle:
        """With OP0_STREAM the first operand is sourced from this rank's
        stream-in port (op0 may be None); with RES_STREAM the result
        lands on the stream-out port (res may be None) — the
        combine-from-stream shape of the reference's plugin datapath."""
        desc = self._prepare(CCLOp.combine, count=count, comm=self.comm,
                             func=func, op0=op0, op1=op1, res=res,
                             stream_dtype=stream_dtype,
                             stream_flags=stream_flags)
        return self._call(desc, run_async, waitfor, chain,
                          retries, retry_policy)

    def send(self, srcbuf: ACCLBuffer | None, count: int, dst: int,
             tag: int = TAG_ANY, *, comm: Communicator | None = None,
             compress_dtype=None, block_scale: bool | int = False,
             stream_dtype=None,
             stream_flags: StreamFlags = StreamFlags.NO_STREAM,
             run_async: bool = False, chain: bool = False,
             waitfor: Sequence[CallHandle] = (),
             retries: int | None = None,
             retry_policy: "RetryPolicy | None" = None
             ) -> CallHandle:
        """With OP0_STREAM the payload is sourced from this rank's
        stream-in port (srcbuf may be None; element type from
        ``stream_dtype``, default float32). ``block_scale`` (with
        ``compress_dtype``) sends block-scaled quantized wire segments
        — the receiver must post a matching block-scaled recv."""
        comm = comm or self.comm
        desc = self._prepare(CCLOp.send, count=count, comm=comm,
                             root_src_dst=dst, tag=tag, op0=srcbuf,
                             compress_dtype=compress_dtype,
                             block_scale=block_scale,
                             stream_dtype=stream_dtype,
                             stream_flags=stream_flags)
        return self._call(desc, run_async, waitfor, chain,
                          retries, retry_policy)

    def recv(self, dstbuf: ACCLBuffer | None, count: int, src: int,
             tag: int = TAG_ANY, *, comm: Communicator | None = None,
             compress_dtype=None, block_scale: bool | int = False,
             stream_dtype=None,
             stream_flags: StreamFlags = StreamFlags.NO_STREAM,
             run_async: bool = False, chain: bool = False,
             waitfor: Sequence[CallHandle] = (),
             retries: int | None = None,
             retry_policy: "RetryPolicy | None" = None
             ) -> CallHandle:
        """With RES_STREAM the received payload lands on this rank's
        stream-out port instead of memory (dstbuf may be None; element
        type from ``stream_dtype``, default float32)."""
        comm = comm or self.comm
        desc = self._prepare(CCLOp.recv, count=count, comm=comm,
                             root_src_dst=src, tag=tag, res=dstbuf,
                             compress_dtype=compress_dtype,
                             block_scale=block_scale,
                             stream_dtype=stream_dtype,
                             stream_flags=stream_flags)
        return self._call(desc, run_async, waitfor, chain,
                          retries, retry_policy)

    def stream_put(self, srcbuf: ACCLBuffer, count: int, dst: int,
                   tag: int = TAG_ANY, *, run_async: bool = False, chain: bool = False,
                   waitfor: Sequence[CallHandle] = (),
                   retries: int | None = None,
                   retry_policy: "RetryPolicy | None" = None
                   ) -> CallHandle:
        """Send into the remote rank's stream port instead of its rx pool
        (reference: remote-stream send, strm tag in the eth header)."""
        desc = self._prepare(CCLOp.send, count=count, comm=self.comm,
                             root_src_dst=dst, tag=tag, op0=srcbuf)
        desc.stream_flags |= StreamFlags.RES_STREAM
        # remote_stream is carried via tag on the move; device backends map
        # RES_STREAM on a send to strm delivery.
        return self._call(desc, run_async, waitfor, chain,
                          retries, retry_policy)

    # -- one-sided RMA (accl_tpu/rma) --------------------------------------
    def register_window(self, buf: ACCLBuffer,
                        window: int | None = None) -> int:
        """Expose ``buf`` as a one-sided window peers can put/get
        against; returns the window id. Ids are the RMA address
        namespace and are exchanged at configure time: when every rank
        registers its windows in the same order, the auto-assigned ids
        agree across ranks without a handshake (pass ``window=`` to pin
        an explicit id instead). The buffer stays usable locally; a
        remote put lands in it with no local call posted."""
        if window is None:
            # counter skips ids pinned explicitly: an auto registration
            # silently stealing a pinned window would redirect every
            # later peer put/get at it into the wrong buffer
            wid = next(self._next_window)
            while wid in self._windows:
                wid = next(self._next_window)
        else:
            wid = int(window)
        self.device.register_window(wid, buf.address, buf.nbytes)
        self._windows[wid] = buf
        return wid

    def deregister_window(self, window: int):
        """Withdraw a window registration: later puts/gets against it
        fail typed (``RMA_WINDOW_ERROR``) at the initiator."""
        self.device.deregister_window(int(window))
        self._windows.pop(int(window), None)

    def put(self, srcbuf: ACCLBuffer, count: int, dst: int, window: int,
            offset: int = 0, *, comm: Communicator | None = None,
            compress_dtype=None, notify: int | None = None,
            run_async: bool = False,
            waitfor: Sequence[CallHandle] = (),
            retries: int | None = None,
            retry_policy: "RetryPolicy | None" = None) -> CallHandle:
        """One-sided write: ``count`` elements of ``srcbuf`` land at byte
        ``offset`` inside window ``window`` on rank ``dst`` (comm-local
        index), which posts NO matching call. Small payloads go eager
        (one frame riding the target's rx pool and tenant quotas); large
        ones rendezvous — RTS/CTS, then segments streamed directly into
        the window, never consuming the target's rx-pool buffers, so a
        multi-MiB KV-cache push cannot starve the pool its
        latency-critical collectives depend on. ``compress_dtype``
        narrows the wire dtype (decompress-on-landing). Completion (the
        data IS in the window) surfaces on the returned handle; chain
        behind compute with ``waitfor=``/``run_async=True``.

        ``notify=token`` (u64) makes the TARGET enqueue one completion
        record on its local notify queue when the put lands (or a typed
        error record when it fails there); the target discovers it with
        :meth:`poll_notifications` — one local dequeue, no collective.
        """
        comm = comm or self.comm
        desc = self._prepare(CCLOp.put, count=count, comm=comm,
                             root_src_dst=dst, tag=int(window), op0=srcbuf,
                             compress_dtype=compress_dtype)
        desc.addr_1 = int(offset)  # byte offset INTO the window (no
        # operand buffer rides addr_1 on one-sided calls)
        if notify is not None:
            # no result buffer rides addr_2 on a put, so it carries the
            # notify token to the device tier (0 = no notification)
            desc.addr_2 = int(notify) & 0xFFFFFFFFFFFFFFFF
        return self._call(desc, run_async, waitfor, False,
                          retries, retry_policy)

    def poll_notifications(self, window: int | None = None,
                           max_records: int = 64):
        """Drain this rank's put-with-notify completion queue: up to
        ``max_records`` :class:`~accl_tpu.rma.NotifyRecord` for
        ``window`` (all windows when None). Purely local — a direct
        device dequeue, not a descriptor call, so it issues NO
        collective and adds no ``accl_calls_total`` rows; a serving loop
        can poll it per decode step at zero wire cost."""
        from .rma.notify import ANY_WINDOW
        wid = ANY_WINDOW if window is None else int(window)
        return self.device.poll_notifications(wid, int(max_records))

    def get(self, dstbuf: ACCLBuffer, count: int, src: int, window: int,
            offset: int = 0, *, comm: Communicator | None = None,
            compress_dtype=None, run_async: bool = False,
            waitfor: Sequence[CallHandle] = (),
            retries: int | None = None,
            retry_policy: "RetryPolicy | None" = None) -> CallHandle:
        """One-sided read: ``count`` elements from byte ``offset`` of
        window ``window`` on rank ``src`` land in ``dstbuf``; the target
        posts no matching call. Same delivery machinery as :meth:`put`
        (the payload streams directly into ``dstbuf`` — requester-pulled
        transfers never buffer in either side's rx pool)."""
        comm = comm or self.comm
        desc = self._prepare(CCLOp.get, count=count, comm=comm,
                             root_src_dst=src, tag=int(window), res=dstbuf,
                             compress_dtype=compress_dtype)
        desc.addr_1 = int(offset)  # byte offset INTO the window
        return self._call(desc, run_async, waitfor, False,
                          retries, retry_policy)

    def stream_push(self, data) -> None:
        """Feed this rank's external-kernel stream-in port: the next call
        with OP0_STREAM sources its operand here (reference: the user
        kernel's AXIS port into the switch, SWITCH_S side)."""
        self.device.push_stream(data)

    def stream_pop(self, timeout: float = 0.0, count: int | None = None):
        """Read from this rank's stream-out port (reference: the AXIS port
        toward the user kernel): ``count`` elements — across however many
        RES_STREAM moves produced them, AXIS continuous-stream semantics —
        or the next entry whole when ``count`` is None. Waits up to
        ``timeout`` seconds; raises IndexError when it never fills."""
        return self.device.pop_stream(timeout, count)

    # -- collectives -------------------------------------------------------
    def bcast(self, buf: ACCLBuffer, count: int | None = None, root: int = 0,
              *, comm: Communicator | None = None,
                 algorithm: CollectiveAlgorithm | str = CollectiveAlgorithm.AUTO,
                 compress_dtype=None, block_scale: bool | int = False,
                 compress_phases: str | None = None,
              run_async: bool = False, chain: bool = False,
              waitfor: Sequence[CallHandle] = (),
              retries: int | None = None,
              retry_policy: "RetryPolicy | None" = None,
              verify_integrity: bool | None = None
              ) -> CallHandle:
        comm = comm or self.comm
        count = count if count is not None else buf.size
        compress_dtype, block_scale = self._resolve_wire(
            "bcast", comm, count, buf.dtype, compress_dtype,
            block_scale)
        routed = self._hier_route("bcast", comm, count,
                                  buf.dtype.itemsize, algorithm)
        if not routed and _phases_strip_flat(compress_phases):
            # strip BEFORE the verify decision (see allreduce)
            compress_dtype, block_scale = None, False
        verify = self._want_verify(verify_integrity, run_async,
                                   compress_dtype is not None)
        if routed:
            with self._retry_scope(retries, retry_policy):
                handle = self._hier.run("bcast", count=count, src=buf,
                                        root=root,
                                        compress_dtype=compress_dtype,
                                        block_scale=block_scale,
                                        compress_phases=compress_phases,
                                        run_async=run_async,
                                        waitfor=waitfor)
            if verify:
                self._verify_result("bcast", buf, count, comm)
            return handle
        desc = self._prepare(CCLOp.bcast, count=count, comm=comm,
                             root_src_dst=root, op0=buf,
                             compress_dtype=compress_dtype,
                             block_scale=block_scale,
                             algorithm=algorithm)
        handle = self._call(desc, run_async, waitfor, chain,
                            retries, retry_policy)
        if verify:
            self._verify_result("bcast", buf, count, comm)
        return handle

    def scatter(self, srcbuf: ACCLBuffer | None, dstbuf: ACCLBuffer,
                count: int, root: int = 0, *,
                comm: Communicator | None = None, compress_dtype=None,
                block_scale: bool | int = False,
                run_async: bool = False, chain: bool = False,
                waitfor: Sequence[CallHandle] = (),
                retries: int | None = None,
                retry_policy: "RetryPolicy | None" = None
                ) -> CallHandle:
        """count = per-rank chunk size; srcbuf holds world_size*count at
        root."""
        comm = comm or self.comm
        desc = self._prepare(CCLOp.scatter, count=count, comm=comm,
                             root_src_dst=root, op0=srcbuf, res=dstbuf,
                             compress_dtype=compress_dtype,
                             block_scale=block_scale)
        return self._call(desc, run_async, waitfor, chain,
                          retries, retry_policy)

    def gather(self, srcbuf: ACCLBuffer, dstbuf: ACCLBuffer | None,
               count: int, root: int = 0, *,
               comm: Communicator | None = None,
                 algorithm: CollectiveAlgorithm | str = CollectiveAlgorithm.AUTO,
                 compress_dtype=None, block_scale: bool | int = False,
               run_async: bool = False, chain: bool = False,
               waitfor: Sequence[CallHandle] = (),
               retries: int | None = None,
               retry_policy: "RetryPolicy | None" = None
               ) -> CallHandle:
        """count = per-rank chunk; dstbuf holds world_size*count at root.
        Non-root ranks may pass None — a scratch relay buffer (the ring
        relay path, reference gather c:632-724) is allocated internally."""
        comm = comm or self.comm
        if comm.local_rank == root:
            if dstbuf is None:
                raise ValueError("gather root requires a destination buffer")
        elif dstbuf is None:
            dstbuf = self._scratch(count, srcbuf.dtype)
        desc = self._prepare(CCLOp.gather, count=count, comm=comm,
                             root_src_dst=root, op0=srcbuf, res=dstbuf,
                             compress_dtype=compress_dtype,
                             block_scale=block_scale,
                             algorithm=algorithm)
        if (desc.algorithm == CollectiveAlgorithm.TREE
                and comm.local_rank != root):
            # TREE gather relays a whole SUBTREE through non-root ranks,
            # not the ring's single chunk: upgrade an undersized scratch
            # (same dtype, so the prepared compression flags still hold)
            from .moveengine import tree_gather_scratch_chunks
            need = tree_gather_scratch_chunks(comm.size, comm.local_rank,
                                              root) * count
            if need and dstbuf.size < need:
                desc.addr_2 = self._scratch(need, dstbuf.dtype).address
        return self._call(desc, run_async, waitfor, chain,
                          retries, retry_policy)

    def reduce(self, srcbuf: ACCLBuffer, dstbuf: ACCLBuffer | None, count: int,
               root: int = 0, func: ReduceFunc = ReduceFunc.SUM, *,
               comm: Communicator | None = None,
                 algorithm: CollectiveAlgorithm | str = CollectiveAlgorithm.AUTO,
                 compress_dtype=None, block_scale: bool | int = False,
               run_async: bool = False, chain: bool = False,
               waitfor: Sequence[CallHandle] = (),
               retries: int | None = None,
               retry_policy: "RetryPolicy | None" = None
               ) -> CallHandle:
        comm = comm or self.comm
        if comm.local_rank == root and dstbuf is None:
            raise ValueError("reduce root requires a destination buffer")
        desc = self._prepare(CCLOp.reduce, count=count, comm=comm,
                             root_src_dst=root, func=func, op0=srcbuf,
                             res=dstbuf, compress_dtype=compress_dtype,
                             block_scale=block_scale,
                             algorithm=algorithm)
        if (desc.algorithm == CollectiveAlgorithm.TREE
                and comm.local_rank != root
                and (dstbuf is None or dstbuf.size < count)):
            # TREE reduce accumulates child partials on internal ranks:
            # substitute an n-element accumulator scratch for an absent
            # OR undersized non-root dst (legal under RING/ROUND_ROBIN,
            # which never write it). Scratch is src-typed, so the RES
            # flag re-derives from the OP0 flag.
            desc.addr_2 = self._scratch(count, srcbuf.dtype).address
            desc.compression &= ~Compression.RES_COMPRESSED
            if desc.compression & Compression.OP0_COMPRESSED:
                desc.compression |= Compression.RES_COMPRESSED
        return self._call(desc, run_async, waitfor, chain,
                          retries, retry_policy)

    def allgather(self, srcbuf: ACCLBuffer, dstbuf: ACCLBuffer, count: int, *,
                  comm: Communicator | None = None,
                 algorithm: CollectiveAlgorithm | str = CollectiveAlgorithm.AUTO,
                 compress_dtype=None, block_scale: bool | int = False,
                 compress_phases: str | None = None,
                  run_async: bool = False, chain: bool = False,
                  waitfor: Sequence[CallHandle] = (),
                  retries: int | None = None,
                  retry_policy: "RetryPolicy | None" = None,
                  verify_integrity: bool | None = None
                  ) -> CallHandle:
        comm = comm or self.comm
        compress_dtype, block_scale = self._resolve_wire(
            "allgather", comm, count,
            srcbuf.dtype if srcbuf.dtype == dstbuf.dtype else None,
            compress_dtype, block_scale)
        routed = self._hier_route(
            "allgather", comm, count,
            max(srcbuf.dtype.itemsize, dstbuf.dtype.itemsize),
            algorithm)
        if not routed and _phases_strip_flat(compress_phases):
            # strip BEFORE the verify decision (see allreduce)
            compress_dtype, block_scale = None, False
        verify = self._want_verify(verify_integrity, run_async,
                                   compress_dtype is not None)
        if routed:
            with self._retry_scope(retries, retry_policy):
                handle = self._hier.run("allgather", count=count,
                                        src=srcbuf, dst=dstbuf,
                                        compress_dtype=compress_dtype,
                                        block_scale=block_scale,
                                        compress_phases=compress_phases,
                                        run_async=run_async,
                                        waitfor=waitfor)
            if verify:
                self._verify_result("allgather", dstbuf,
                                    count * comm.size, comm)
            return handle
        desc = self._prepare(CCLOp.allgather, count=count, comm=comm,
                             op0=srcbuf, res=dstbuf,
                             compress_dtype=compress_dtype,
                             block_scale=block_scale,
                             algorithm=algorithm)
        handle = self._call(desc, run_async, waitfor, chain,
                            retries, retry_policy)
        if verify:
            # the replicated result is the whole gathered vector
            self._verify_result("allgather", dstbuf, count * comm.size,
                                comm)
        return handle

    def allreduce(self, srcbuf: ACCLBuffer, dstbuf: ACCLBuffer, count: int,
                  func: ReduceFunc = ReduceFunc.SUM, *,
                  comm: Communicator | None = None,
                 algorithm: CollectiveAlgorithm | str = CollectiveAlgorithm.AUTO,
                 compress_dtype=None, block_scale: bool | int = False,
                 compress_phases: str | None = None,
                  run_async: bool = False, chain: bool = False,
                  waitfor: Sequence[CallHandle] = (),
                  retries: int | None = None,
                  retry_policy: "RetryPolicy | None" = None,
                  verify_integrity: bool | None = None
                  ) -> CallHandle:
        """``compress_dtype`` narrows the wire; with ``block_scale``
        (True = tuner-recommended block, int = explicit) the wire is
        block-scale QUANTIZED instead — per-segment scale headers, f32
        accumulation, per-hop-bounded error (accl_tpu/quant.py).
        ``compress_dtype="auto"`` lets the tuner pick quantized wire in
        the bandwidth-bound band. ``compress_phases="inter"`` applies
        the wire compression only to phases that cross the slow
        inter-host tier of a HIERARCHICAL lowering (EQuARX's headline
        trick); intra-host phases stay full precision, and a flat call
        with "inter" is simply uncompressed."""
        comm = comm or self.comm
        compress_dtype, block_scale = self._resolve_wire(
            "allreduce", comm, count,
            srcbuf.dtype if srcbuf.dtype == dstbuf.dtype else None,
            compress_dtype, block_scale)
        routed = self._hier_route(
            "allreduce", comm, count,
            max(srcbuf.dtype.itemsize, dstbuf.dtype.itemsize),
            algorithm)
        if not routed and _phases_strip_flat(compress_phases):
            # strip BEFORE the verify decision: a flat "inter" call
            # executes fully uncompressed, where verification is valid
            compress_dtype, block_scale = None, False
        verify = self._want_verify(verify_integrity, run_async,
                                   compress_dtype is not None)
        if routed:
            with self._retry_scope(retries, retry_policy):
                handle = self._hier.run("allreduce", count=count,
                                        src=srcbuf, dst=dstbuf, func=func,
                                        compress_dtype=compress_dtype,
                                        block_scale=block_scale,
                                        compress_phases=compress_phases,
                                        run_async=run_async,
                                        waitfor=waitfor)
            if verify:
                self._verify_result("allreduce", dstbuf, count, comm)
            return handle
        desc = self._prepare(CCLOp.allreduce, count=count, comm=comm,
                             func=func, op0=srcbuf, res=dstbuf,
                             compress_dtype=compress_dtype,
                             block_scale=block_scale,
                             algorithm=algorithm)
        handle = self._call(desc, run_async, waitfor, chain,
                            retries, retry_policy)
        if verify:
            self._verify_result("allreduce", dstbuf, count, comm)
        return handle

    def reduce_scatter(self, srcbuf: ACCLBuffer, dstbuf: ACCLBuffer,
                       count: int, func: ReduceFunc = ReduceFunc.SUM, *,
                       comm: Communicator | None = None,
                 algorithm: CollectiveAlgorithm | str = CollectiveAlgorithm.AUTO,
                       compress_dtype=None, block_scale: bool | int = False,
                       compress_phases: str | None = None,
                       run_async: bool = False, chain: bool = False,
                       waitfor: Sequence[CallHandle] = (),
                       retries: int | None = None,
                       retry_policy: "RetryPolicy | None" = None
                       ) -> CallHandle:
        """count = per-rank chunk; srcbuf holds world_size*count."""
        comm = comm or self.comm
        compress_dtype, block_scale = self._resolve_wire(
            "reduce_scatter", comm, count,
            srcbuf.dtype if srcbuf.dtype == dstbuf.dtype else None,
            compress_dtype, block_scale)
        if self._hier_route(
                "reduce_scatter", comm, count,
                max(srcbuf.dtype.itemsize, dstbuf.dtype.itemsize),
                algorithm):
            with self._retry_scope(retries, retry_policy):
                return self._hier.run("reduce_scatter", count=count,
                                      src=srcbuf, dst=dstbuf, func=func,
                                      compress_dtype=compress_dtype,
                                      block_scale=block_scale,
                                      compress_phases=compress_phases,
                                      run_async=run_async, waitfor=waitfor)
        if _phases_strip_flat(compress_phases):
            compress_dtype, block_scale = None, False
        desc = self._prepare(CCLOp.reduce_scatter, count=count, comm=comm,
                             func=func, op0=srcbuf, res=dstbuf,
                             compress_dtype=compress_dtype,
                             block_scale=block_scale,
                             algorithm=algorithm)
        if desc.algorithm == CollectiveAlgorithm.RECURSIVE_DOUBLING:
            # the recursive-halving expansion needs a whole-vector
            # working buffer of partial sums (uncompressed dtype),
            # plumbed through the descriptor's otherwise-unused op1 slot
            desc.addr_1 = self._scratch(
                comm.size * count,
                desc.arithcfg.uncompressed_dtype).address
        return self._call(desc, run_async, waitfor, chain,
                          retries, retry_policy)

    def alltoall(self, srcbuf: ACCLBuffer, dstbuf: ACCLBuffer, count: int, *,
                 comm: Communicator | None = None, compress_dtype=None,
                 block_scale: bool | int = False,
                 run_async: bool = False, chain: bool = False,
                 waitfor: Sequence[CallHandle] = (),
                 retries: int | None = None,
                 retry_policy: "RetryPolicy | None" = None
                 ) -> CallHandle:
        comm = comm or self.comm
        desc = self._prepare(CCLOp.alltoall, count=count, comm=comm,
                             op0=srcbuf, res=dstbuf,
                             compress_dtype=compress_dtype,
                             block_scale=block_scale)
        return self._call(desc, run_async, waitfor, chain,
                          retries, retry_policy)

    def alltoallv(self, srcbuf: ACCLBuffer, dstbuf: ACCLBuffer,
                  send_counts: Sequence[int], recv_counts: Sequence[int], *,
                  comm: Communicator | None = None, compress_dtype=None,
                  block_scale: bool | int = False,
                  run_async: bool = False, chain: bool = False,
                  waitfor: Sequence[CallHandle] = (),
                  retries: int | None = None,
                  retry_policy: "RetryPolicy | None" = None
                  ) -> CallHandle:
        """Variable-count all-to-all (MPI_Alltoallv, contiguous
        displacements): this rank sends ``send_counts[d]`` elements to
        rank d from the d-th interval of ``srcbuf`` (intervals tile the
        buffer in rank order) and receives ``recv_counts[s]`` elements
        from rank s into the s-th interval of ``dstbuf``. Count vectors
        must be pairwise consistent across ranks (rank i's
        ``send_counts[j]`` == rank j's ``recv_counts[i]``) — that is a
        cross-rank contract this driver cannot check locally; a mismatch
        surfaces as a recv deadline or a DMA size error on the shorter
        side. Zero-count peers exchange nothing (skewed MoE routing
        routinely zeroes most of the vector). ``compress_dtype=``/
        ``block_scale=`` ride the fp8 block-scaled wire exactly like the
        fixed-count collectives ("auto" prices the quantized wire via
        the tuner); ``chain=``/``waitfor=`` compose with the plan cache
        so repeated uneven exchanges pipeline behind compute.

        Overlapping ``srcbuf``/``dstbuf`` (in-place) are staged through
        a scratch copy of the send region: uneven intervals can alias
        across DIFFERENT peers' chunks, which no lane-local hazard edge
        can order, so the engine is only ever given disjoint regions."""
        comm = comm or self.comm
        W = comm.size
        send_counts = tuple(int(c) for c in send_counts)
        recv_counts = tuple(int(c) for c in recv_counts)
        if len(send_counts) != W or len(recv_counts) != W:
            raise ValueError(
                f"alltoallv count vectors must have comm.size={W} "
                f"entries; got {len(send_counts)} send / "
                f"{len(recv_counts)} recv")
        if min(send_counts + recv_counts) < 0:
            raise ValueError("alltoallv counts must be non-negative")
        n_send, n_recv = sum(send_counts), sum(recv_counts)
        if srcbuf.size < n_send or dstbuf.size < n_recv:
            raise ValueError(
                f"count vectors overflow their buffers: send needs "
                f"{n_send} elems (srcbuf {srcbuf.size}), recv needs "
                f"{n_recv} (dstbuf {dstbuf.size})")
        count = max(n_send, n_recv)
        compress_dtype, block_scale = self._resolve_wire(
            "alltoallv", comm, count,
            srcbuf.dtype if srcbuf.dtype == dstbuf.dtype else None,
            compress_dtype, block_scale)
        # uneven-exchange observability (docs/OBSERVABILITY.md): the
        # count-vector shape is what distinguishes this op — record the
        # port bytes and the skew (largest peer chunk over the even
        # share) so a routing collapse (all tokens to one expert rank)
        # is visible without a trace
        METRICS.inc("alltoallv_total", rank=self.rank)
        METRICS.inc("alltoallv_bytes_total",
                    count * srcbuf.dtype.itemsize, rank=self.rank)
        zero_peers = (sum(1 for c in send_counts if not c)
                      + sum(1 for c in recv_counts if not c))
        if zero_peers:
            METRICS.inc("alltoallv_zero_peers_total", zero_peers,
                        rank=self.rank)
        if count:
            cmax = max(max(send_counts), max(recv_counts))
            METRICS.set_gauge("alltoallv_skew",
                              round(cmax * W / count, 3), rank=self.rank)
        src_arena = srcbuf
        stage_pool = None
        a0, a1 = srcbuf.address, srcbuf.address + srcbuf.nbytes
        b0, b1 = dstbuf.address, dstbuf.address + dstbuf.nbytes
        if n_send and a0 < b1 and b0 < a1:
            if run_async:
                # private recycled stage (the redistribute pool): a
                # cached scratch would be shared by a second in-flight
                # exchange whose staging copy could overwrite bytes this
                # call's sends are still reading
                pk = (srcbuf.size, srcbuf.dtype.name)
                stage_pool = self._redist_stage_pool.setdefault(pk, [])
                src_arena = stage_pool.pop() if stage_pool else \
                    self.buffer((srcbuf.size,), srcbuf.dtype)
            else:
                src_arena = self._scratch(srcbuf.size, srcbuf.dtype)
            cp = self.copy(srcbuf[0:n_send], src_arena[0:n_send], n_send,
                           comm=comm, run_async=True, waitfor=waitfor)
            waitfor = (cp,)
        desc = self._prepare(CCLOp.alltoallv, count=count, comm=comm,
                             op0=src_arena, res=dstbuf,
                             compress_dtype=compress_dtype,
                             block_scale=block_scale)
        desc.counts = (send_counts, recv_counts)
        ret = self._call(desc, run_async, waitfor, chain,
                         retries, retry_policy)
        if stage_pool is not None:
            pool, buf = stage_pool, src_arena
            ret.add_done_callback(lambda _err: pool.append(buf))
        return ret

    def redistribute(self, srcbuf: ACCLBuffer, src_spec,
                     dstbuf: ACCLBuffer, dst_spec, *,
                     comm: Communicator | None = None,
                     members: Sequence[int] | None = None,
                     compress_dtype=None, run_async: bool = False,
                     waitfor: Sequence[CallHandle] = (),
                     retries: int | None = None,
                     retry_policy: "RetryPolicy | None" = None
                     ) -> CallHandle:
        """Change an array's sharding: ``srcbuf`` holds this rank's
        shard under ``src_spec`` (:class:`~accl_tpu.hier.ShardSpec`),
        and on completion ``dstbuf`` holds its shard under ``dst_spec``.

        The compiler (accl_tpu/hier/redistribute.py) lowers the spec
        pair to the minimal program the change admits — local slice
        copies, one allgather, one alltoall, one alltoallv (dense
        uneven block exchanges), or rotated point-to-point sends — and
        this driver executes it over ``comm`` (default: the
        world). ``members`` restricts the exchange to a world-rank
        subset: the driver derives (and caches) the sub-communicator,
        and both specs must span ``len(members)`` ranks. Overlapping
        src/dst buffers (in-place resharding) are staged through a
        scratch copy of the source shard. Every issued sub-call's
        CallRecord carries this logical call's tag as ``parent``."""
        import time as _time

        from .hier import plan_redistribute
        if members is not None:
            if comm is not None:
                # mutually exclusive in effect: members derives its own
                # sub-communicator, which would silently bypass the
                # passed comm (and any tenant/QoS state on it)
                raise ValueError(
                    "pass either comm= or members=, not both (members "
                    "derives its own sub-communicator of those world "
                    "ranks)")
            members = tuple(int(m) for m in members)
            comm = self._redist_comms.get(members)
            if comm is None:
                comm = self.split_communicator(list(members), key=0x52ED)
                self._redist_comms[members] = comm
        else:
            comm = comm or self.comm
        if src_spec.world != comm.size or dst_spec.world != comm.size:
            raise ValueError(
                f"spec worlds ({src_spec.world}, {dst_spec.world}) do "
                f"not match the communicator size {comm.size}")
        if srcbuf.dtype != dstbuf.dtype:
            raise ValueError(
                f"redistribute moves bytes, not values: src dtype "
                f"{srcbuf.dtype.name} != dst dtype {dstbuf.dtype.name} "
                f"(use compress_dtype for wire compression)")
        me = comm.local_rank
        src_count = src_spec.local_count(me)
        dst_count = dst_spec.local_count(me)
        if srcbuf.size < src_count or dstbuf.size < dst_count:
            raise ValueError(
                f"shard does not fit its buffer: src needs {src_count} "
                f"elems (buffer {srcbuf.size}), dst needs {dst_count} "
                f"(buffer {dstbuf.size})")
        pk = (src_spec, dst_spec, me)
        plan = self._redist_plans.get(pk)
        if plan is None:
            plan = plan_redistribute(src_spec, dst_spec, me)
            self._redist_plans[pk] = plan
        tag = f"redist#{next(self._redist_seq)}"
        key = ("redistribute", comm.comm_id)
        self._call_counts[key] = self._call_counts.get(key, 0) + 1
        # reshard observability (elastic membership rides on these):
        # rare-by-construction direct registry writes, like the fabric
        # fault counters — a reshard is a membership-scale event, not a
        # per-frame hot path
        nbytes_global = src_spec.n * srcbuf.dtype.itemsize
        METRICS.inc("reshard_total", rank=self.rank, kind=plan.kind)
        METRICS.inc("reshard_bytes_total", nbytes_global, rank=self.rank)
        if TRACE.enabled:
            TRACE.emit("reshard", rank=self.rank, nbytes=nbytes_global,
                       peer=-1)
        t0 = _time.perf_counter()

        def _slice(buf, off, n):
            if off == 0 and n == buf.size:
                return buf
            return buf[off:off + n]

        # validate shapes BEFORE issuing anything, and UNIFORMLY across
        # ranks: plans differ per rank (one rank's slices, another's
        # whole-buffer transfers), so a slicing-aware rank-local check
        # would raise on some ranks while their peers sail into recvs
        # that only fail by timeout — and the p2p program's eager sends
        # complete into peer rx pools, where a mid-program abort would
        # strand frames for a later TAG_ANY transfer to mis-match.
        # Hence the blanket contract: shard buffers are 1-D (flat
        # element layout).
        if plan.kind != "noop" and (len(srcbuf.shape) != 1
                                    or len(dstbuf.shape) != 1):
            raise ValueError(
                "redistribute addresses sub-ranges of the shard "
                "buffers; pass 1-D buffers (flat element layout)")

        # in-place resharding: stage the source shard so no transfer
        # reads bytes another transfer of the same program rewrites
        src_arena = srcbuf
        a0, a1 = srcbuf.address, srcbuf.address + srcbuf.nbytes
        b0, b1 = dstbuf.address, dstbuf.address + dstbuf.nbytes
        stage_pool = None
        if plan.kind != "noop" and a0 < b1 and b0 < a1:
            if run_async:
                # a cached stage would be shared by a second async
                # redistribute of the same shard size whose staging copy
                # could overwrite bytes the first call's sends (on a
                # DIFFERENT communicator — no FIFO ordering between
                # them) are still reading; async in-place reshards draw
                # a private buffer from a recycled pool (a fresh alloc
                # per call would grow the device-registered memory
                # without bound — buffers are returned by the program's
                # completion callback below)
                pk2 = (srcbuf.size, srcbuf.dtype.name)
                stage_pool = self._redist_stage_pool.setdefault(pk2, [])
                stage = stage_pool.pop() if stage_pool else \
                    self.buffer((srcbuf.size,), srcbuf.dtype)
            else:
                sk = ("redist-stage", srcbuf.size, srcbuf.dtype.name)
                stage = self._scratch_bufs.get(sk)
                if stage is None:
                    stage = self.buffer((srcbuf.size,), srcbuf.dtype)
                    self._scratch_bufs[sk] = stage
            src_arena = stage
        handles: list[CallHandle] = []
        with self._retry_scope(retries, retry_policy), \
                self._attributed(tag):
            if src_arena is not srcbuf and src_count:
                handles.append(self.copy(
                    _slice(srcbuf, 0, src_count),
                    _slice(src_arena, 0, src_count), src_count,
                    comm=comm, run_async=True, waitfor=waitfor))
                waitfor = (handles[-1],)
            if plan.kind == "allgather":
                handles.append(self.allgather(
                    _slice(src_arena, 0, src_count), dstbuf,
                    plan.coll_count, comm=comm,
                    compress_dtype=compress_dtype, run_async=True,
                    waitfor=waitfor))
            elif plan.kind == "alltoall":
                handles.append(self.alltoall(
                    _slice(src_arena, 0, src_count),
                    _slice(dstbuf, 0, dst_count), plan.coll_count,
                    comm=comm, compress_dtype=compress_dtype,
                    run_async=True, waitfor=waitfor))
            elif plan.kind == "alltoallv":
                # dense uneven reshard: the whole interval-ownership
                # program is one variable-count collective (the plan's
                # vectors tile the shards by construction, and src was
                # staged above if in-place, so the collective never
                # sees aliasing buffers)
                handles.append(self.alltoallv(
                    _slice(src_arena, 0, src_count),
                    _slice(dstbuf, 0, dst_count),
                    plan.send_counts, plan.recv_counts,
                    comm=comm, compress_dtype=compress_dtype,
                    run_async=True, waitfor=waitfor))
            else:
                for st in plan.steps:
                    if st.kind == "send":
                        handles.append(self.send(
                            _slice(src_arena, st.src_off, st.count),
                            st.count, dst=st.peer, comm=comm,
                            compress_dtype=compress_dtype,
                            run_async=True, waitfor=waitfor))
                    elif st.kind == "recv":
                        handles.append(self.recv(
                            _slice(dstbuf, st.dst_off, st.count),
                            st.count, src=st.peer, comm=comm,
                            compress_dtype=compress_dtype,
                            run_async=True, waitfor=waitfor))
                    else:
                        handles.append(self.copy(
                            _slice(src_arena, st.src_off, st.count),
                            _slice(dstbuf, st.dst_off, st.count),
                            st.count, comm=comm, run_async=True,
                            waitfor=waitfor))
        if run_async:
            if not handles:
                # nothing to issue (noop plan) — but the returned handle
                # must still carry the caller's waitfor ordering, like
                # the sync path's wait_all(waitfor) does
                handles = list(waitfor)
            if not handles:
                return CompletedHandle(context="redistribute")
            if len(handles) == 1:
                ret = handles[0]
            else:
                # no single sub-call handle is guaranteed last: the
                # device's FIFO retirement contract is per-comm
                # SUBMISSION order, but local copies may retire inline
                # while transfers drain on workers. Aggregate: complete
                # when EVERY sub-call has, with the OR of their error
                # words (first exception kept).
                import threading as _threading
                agg = CallHandle(context="redistribute")
                mu = _threading.Lock()
                state = {"left": len(handles), "err": 0, "exc": None}

                def _one_done(h):
                    def cb(err):
                        with mu:
                            state["err"] |= int(err)
                            if state["exc"] is None \
                                    and h._exception is not None:
                                state["exc"] = h._exception
                            state["left"] -= 1
                            done = state["left"] == 0
                        if done:
                            agg.complete(state["err"],
                                         exception=state["exc"])
                    return cb

                for h in handles:
                    h.add_done_callback(_one_done(h))
                ret = agg
            if stage_pool is not None:
                # recycle the private stage only when the WHOLE program
                # has retired (the aggregate — a single sub-call handle
                # could complete while a transfer on the other
                # communicator still reads the stage)
                pool, buf = stage_pool, src_arena
                ret.add_done_callback(lambda _err: pool.append(buf))
            return ret
        from .call import wait_all
        wait_all(handles if handles else list(waitfor))
        if self.profiler.enabled:
            from .tracing import CallRecord
            self.profiler.record(CallRecord(
                op="redistribute", count=src_spec.n,
                nbytes=src_spec.n * srcbuf.dtype.itemsize,
                comm_id=comm.comm_id, t_start=t0,
                duration_s=_time.perf_counter() - t0,
                algorithm=plan.kind.upper(), parent=tag,
                tenant=self.tenant or f"comm-{comm.comm_id}"))
        return CompletedHandle(context="redistribute")

    def barrier(self, *, comm: Communicator | None = None,
                waitfor: Sequence[CallHandle] = (),
                retries: int | None = None,
                retry_policy: "RetryPolicy | None" = None
                ) -> CallHandle:
        """Rendezvous of all ranks: a 1-element allreduce on a scratch
        buffer (the reference leans on host-side MPI barriers; we make it a
        first-class op)."""
        comm = comm or self.comm
        if self._barrier_buf is None:
            self._barrier_buf = self.buffer((2,), np.float32)
        buf = self._barrier_buf
        desc = self._prepare(CCLOp.allreduce, count=1, comm=comm,
                             op0=buf[0:1], res=buf[1:2])
        return self._call(desc, False, waitfor, False, retries,
                          retry_policy)

    # -- introspection (parity: accl.py:412-526, 710-735) ------------------
    def plan_cache_stats(self) -> dict:
        """Compiled-plan cache counters of this rank's backend (hits,
        misses, bypasses, evictions, per-reason invalidations), or an
        ``{"enabled": False}`` stub on backends without a plan cache.
        Per-call hit/miss/bypass is also on every profiled
        :class:`~accl_tpu.tracing.CallRecord` (``plan_cache`` field)."""
        cache = getattr(self.device, "plan_cache", None)
        if cache is None:
            return {"enabled": False, "entries": 0, "hits": 0, "misses": 0,
                    "bypasses": 0, "evictions": 0, "invalidations": {}}
        return cache.stats()

    def dump_communicator(self) -> str:
        return self.comm.describe()

    def dump_rx_buffers(self) -> str:
        pool = getattr(self.device, "pool", None)
        return pool.describe() if pool is not None else "<no rx pool>"
