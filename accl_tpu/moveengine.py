"""Control plane: expand collective calls into ``Move`` micro-operations.

This is the TPU-framework equivalent of the reference's MicroBlaze firmware
(kernels/cclo/fw/sw_apps/ccl_offload_control/src/ccl_offload_control.c):
every primitive/collective is expressed as a short program of generic *move*
micro-ops, each of which reads up to two operands (from memory, from the
receive-matching engine, or from a stream), optionally combines them
elementwise, and writes the result locally and/or sends it to a peer.

Design differences from the reference (deliberate, TPU-idiomatic):
  * The firmware resolves INCREMENT/REPEAT/STRIDE address modes *inside the
    dataplane* with per-channel previous-address registers
    (dma_mover.cpp:497-669). Here the engine resolves concrete byte
    addresses at expansion time and records the mode label for parity
    inspection — software expansion makes stateful address registers
    pointless.
  * Counts are elements of the call's uncompressed dtype; addresses are byte
    offsets into the rank's device memory.

Collective expansions mirror the reference algorithms one-for-one so a
reviewer can diff them against ccl_offload_control.c:502-1098:
ring gather/allgather/reduce/reduce_scatter, 2-phase ring allreduce
(fused reduce-scatter + allgather), segmented broadcast, strided scatter.
Beyond the reference's ring/round-robin firmware, a log-depth family
(recursive doubling/halving, Rabenseifner allreduce, binomial trees —
see the section comment above expand_allgather_recursive_doubling)
covers the small-message regime where serialized alpha hops dominate.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Iterator

from .arith import ArithConfig
from .constants import (CCLOp, CollectiveAlgorithm, Compression,
                        DEFAULT_ALGORITHMS, ReduceFunc, StreamFlags,
                        TAG_ANY, VALID_ALGORITHMS, check_algorithm)


def res_as_op0(compression: Compression) -> Compression:
    """Remap the RES compressed-ness onto OP0: used when a follow-on stage
    reads the previous stage's result buffer as its operand (e.g. the
    bcast after a non-fused reduce, or the root folding into dst)."""
    out = compression & ~Compression.OP0_COMPRESSED
    if compression & Compression.RES_COMPRESSED:
        out |= Compression.OP0_COMPRESSED
    return out


class MoveMode(enum.Enum):
    """Operand sourcing/sinking modes.

    Parity: MOVE_NONE/STREAM/IMMEDIATE/ON_RECV/INCREMENT/REPEAT/STRIDE
    (ccl_offload_control.h:153-161). INCREMENT/REPEAT/STRIDE collapse to
    IMMEDIATE at expansion time; the ``mode_label`` field on Move keeps the
    original mode name for diffing against the firmware.
    """

    NONE = "none"
    IMMEDIATE = "immediate"
    ON_RECV = "on_recv"
    STREAM = "stream"


@dataclasses.dataclass
class Operand:
    mode: MoveMode = MoveMode.NONE
    addr: int | None = None          # byte address (IMMEDIATE)
    src_rank: int | None = None      # peer to match (ON_RECV)
    tag: int = TAG_ANY               # envelope tag (ON_RECV)
    compressed: bool = False         # operand stored in compressed dtype

    @classmethod
    def none(cls):
        return cls(MoveMode.NONE)

    @classmethod
    def imm(cls, addr: int, compressed: bool = False):
        return cls(MoveMode.IMMEDIATE, addr=addr, compressed=compressed)

    @classmethod
    def on_recv(cls, src_rank: int, tag: int = TAG_ANY):
        return cls(MoveMode.ON_RECV, src_rank=src_rank, tag=tag)

    @classmethod
    def stream(cls):
        return cls(MoveMode.STREAM)


@dataclasses.dataclass
class Move:
    """One micro-op: res = func(op0, op1), written locally and/or sent.

    Parity: ``move_instruction`` (dma_mover.h:28-74) — op0/op1/res operand
    specs, elementwise function, remote destination {rank, tag}, compression
    flags, count. ``blocking`` marks moves whose result must be fully
    retired before the next move may start (the reference forces this where
    a relay would race a concurrent write, ccl_offload_control.c:788-791).

    ``blocking=False`` invariant (what the pipelined executor relies on —
    audit every site that clears the flag against it): the move is a pure
    pool-destined send (no local write, no stream port) AND no later move
    of the same program writes the memory it reads — except moves of the
    send's OWN lane, whose lane chain orders the writer behind the send
    (in-place alltoall's paired exchange and the Rabenseifner rounds'
    chunk reuse rely on this lane-local exception). Such a move may
    retire asynchronously, overlapping subsequent moves; the executor
    keeps wire sequence numbers in program order regardless. A send whose
    source is rewritten later OUTSIDE its lane (gather's relay scratch,
    c:632-724) must stay blocking.

    ``lane`` invariant (what the segment-streamed executor relies on): a
    move tagged with a segment lane may execute concurrently with moves of
    OTHER lanes; within one lane, program order is preserved. The
    expansion tagging lane ``s`` therefore asserts that every byte the
    move reads or writes is disjoint from the bytes touched by every
    *concurrent* move of a different lane — segment ``s`` of step ``k+1``
    depends only on segment ``s`` of step ``k``, never on a sibling
    segment (the reference's dual-DataMover segment interleave,
    dma_mover.cpp:716-898). Moves whose hazards cannot be expressed that
    way (gather's reused relay scratch, stream-port moves) carry
    ``lane=None`` and serialize as barriers. Lane-chaining follows program
    order, so the implied dependency graph is acyclic by construction
    (``scripts/check_blocking.py`` lints both invariants).
    """

    count: int
    op0: Operand = dataclasses.field(default_factory=Operand.none)
    op1: Operand = dataclasses.field(default_factory=Operand.none)
    res: Operand = dataclasses.field(default_factory=Operand.none)
    func: ReduceFunc | None = None
    res_remote: bool = False
    res_local: bool = False
    dst_rank: int | None = None      # remote destination rank
    tag: int = 0                     # tag for the outgoing message
    eth_compressed: bool = False     # compress on the wire
    # block-scaled quantized wire (accl_tpu/quant.py): this move's wire
    # traffic carries scale-block payloads — emission quantizes, ON_RECV
    # operands dequantize, and cut-through fusion must NOT forward the
    # in-hand payload (a re-read requantizes with fresh scales, so the
    # serial oracle's relay bytes differ from the forwarded original).
    # Set by expand_call's post-pass from Compression.BLOCK_SCALED, so
    # per-site expansion code cannot drift.
    block_scaled: bool = False
    remote_stream: bool = False      # deliver to peer's stream, not rx pool
    blocking: bool = True
    lane: int | None = None          # segment lane (see class docstring)
    mode_label: str = ""             # firmware address-mode annotation


def _seg_elems(arithcfg: ArithConfig, max_segment_size: int,
               eth_compressed: bool) -> int:
    """Elements per wire segment.

    Parity: the firmware computes segment element count from
    max_segment_size / elem bytes, using the *wire* element size when the
    message is compressed (broadcast, ccl_offload_control.c:530-535).
    Block-scaled wire (arithcfg.quant_block > 0) additionally reserves
    the scale-header overhead so the PACKED payload still fits the
    segment (and thus the rx buffer) — via quant.seg_elems, whose
    reservation is block-size-independent so compiled plans never key on
    the runtime block choice.
    """
    if eth_compressed and arithcfg.quant_block > 0:
        from .quant import seg_elems
        return seg_elems(max_segment_size, arithcfg.compressed_elem_bytes)
    elem = (arithcfg.compressed_elem_bytes if eth_compressed
            else arithcfg.uncompressed_elem_bytes)
    return max(1, max_segment_size // max(1, elem))


def _segments(count: int, seg: int) -> Iterator[tuple[int, int]]:
    """Yield (offset_elems, nelems) chunks of a count."""
    off = 0
    while off < count:
        n = min(seg, count - off)
        yield off, n
        off += n


@dataclasses.dataclass
class MoveContext:
    """Everything an expansion needs besides the call itself."""

    world_size: int
    local_rank: int
    arithcfg: ArithConfig
    max_segment_size: int
    # Optional attached Tuner (accl_tpu/tuner): consulted by expand_call
    # when a descriptor still carries CollectiveAlgorithm.AUTO at the
    # engine (duck-typed — anything with .select(op, world, nbytes)).
    tuner: Any = None

    def ebytes(self, compressed: bool = False) -> int:
        return (self.arithcfg.compressed_elem_bytes if compressed
                else self.arithcfg.uncompressed_elem_bytes)


# ---------------------------------------------------------------------------
# Primitives (parity: ccl_offload_control.c:301-500)
# ---------------------------------------------------------------------------

def expand_copy(ctx: MoveContext, count: int, src: int, dst: int,
                compression: Compression = Compression.NONE,
                stream: StreamFlags = StreamFlags.NO_STREAM) -> list[Move]:
    """copy (c:301-315): one local move op0->res."""
    op0 = (Operand.stream() if stream & StreamFlags.OP0_STREAM
           else Operand.imm(src, bool(compression & Compression.OP0_COMPRESSED)))
    res = (Operand.stream() if stream & StreamFlags.RES_STREAM
           else Operand.imm(dst, bool(compression & Compression.RES_COMPRESSED)))
    return [Move(count=count, op0=op0, res=res, res_local=True,
                 mode_label="IMMEDIATE/NONE/IMMEDIATE")]


def expand_combine(ctx: MoveContext, count: int, func: ReduceFunc,
                   op0: int, op1: int, dst: int,
                   compression: Compression = Compression.NONE,
                   stream: StreamFlags = StreamFlags.NO_STREAM) -> list[Move]:
    """combine (c:319-335): res = func(op0, op1) locally. OP0/RES stream
    flags source the first operand from / sink the result to the
    external-kernel ports, like copy (the combine-from-stream shape of
    the reference's plugin datapath)."""
    s_op0 = bool(stream & StreamFlags.OP0_STREAM)
    s_res = bool(stream & StreamFlags.RES_STREAM)
    return [Move(
        count=count,
        op0=(Operand.stream() if s_op0
             else Operand.imm(op0,
                              bool(compression & Compression.OP0_COMPRESSED))),
        op1=Operand.imm(op1, bool(compression & Compression.OP1_COMPRESSED)),
        res=(Operand.stream() if s_res
             else Operand.imm(dst,
                              bool(compression & Compression.RES_COMPRESSED))),
        func=func, res_local=True,
        mode_label=(f"{'STREAM' if s_op0 else 'IMMEDIATE'}/IMMEDIATE/"
                    f"{'STREAM' if s_res else 'IMMEDIATE'}"))]


def expand_send(ctx: MoveContext, count: int, src: int, dst_rank: int,
                tag: int = 0,
                compression: Compression = Compression.NONE,
                stream: StreamFlags = StreamFlags.NO_STREAM,
                to_remote_stream: bool = False,
                blocking: bool = True, laned: bool = False,
                lane_base: int | None = None) -> list[Move]:
    """send (c:339-361): segmented op0 -> remote res.

    Wire compression applies when ETH_COMPRESSED is set; segmentation at
    max_segment_size like the eth_cmd split (dma_mover.cpp:280-318).
    ``blocking=False`` is passed by callers whose source region is never
    written later in the program (see the Move.blocking invariant) so the
    pipelined executor can overlap the send with subsequent moves.
    ``laned=True`` additionally tags each segment with its lane — callers
    assert the Move.lane invariant: segment ``s`` reads only bytes written
    by earlier moves of lane ``s`` (the relay-from-slot shape).
    ``lane_base`` (implies laned) offsets the lane ids — the log-depth
    expansions lane per GLOBAL chunk (lane = chunk * segs_per_chunk + s)
    so a chunk's reader in round k+1 chains behind the same chunk's
    writer in round k even though the two moves cover different regions.
    """
    eth_c = bool(compression & Compression.ETH_COMPRESSED)
    moves = []
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size, eth_c)
    ebytes = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    if lane_base is not None:
        laned = True
    for si, (off, n) in enumerate(_segments(count, seg)):
        op0 = (Operand.stream() if stream & StreamFlags.OP0_STREAM
               else Operand.imm(src + off * ebytes,
                                bool(compression & Compression.OP0_COMPRESSED)))
        moves.append(Move(count=n, op0=op0, res_remote=True,
                          dst_rank=dst_rank, tag=tag, eth_compressed=eth_c,
                          remote_stream=to_remote_stream, blocking=blocking,
                          lane=((lane_base or 0) + si) if laned else None,
                          mode_label="IMMEDIATE/NONE/REMOTE"))
    return moves


def expand_recv(ctx: MoveContext, count: int, src_rank: int, dst: int,
                tag: int = 0,
                compression: Compression = Compression.NONE,
                stream: StreamFlags = StreamFlags.NO_STREAM,
                laned: bool = True,
                lane_base: int | None = None) -> list[Move]:
    """recv (c:365-380): segmented ON_RECV -> local res.

    Each segment carries its lane tag: segment ``s`` writes only its own
    slice of ``dst``, so recv-matching of segment ``s+1`` may overlap the
    consumption of segment ``s`` (Move.lane invariant; the one consumer
    that re-reads the written slice — a relay — rides the SAME lane).
    ``laned=False`` is for documented barrier phases (the log-depth vrank
    fold-in/fold-out), whose whole-result transfers span regions written
    by many lanes; ``lane_base`` offsets lane ids for global-chunk laning
    (see expand_send).
    """
    eth_c = bool(compression & Compression.ETH_COMPRESSED)
    moves = []
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size, eth_c)
    ebytes = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    for si, (off, n) in enumerate(_segments(count, seg)):
        res = (Operand.stream() if stream & StreamFlags.RES_STREAM
               else Operand.imm(dst + off * ebytes,
                                bool(compression & Compression.RES_COMPRESSED)))
        moves.append(Move(count=n, op1=Operand.on_recv(src_rank, tag),
                          res=res, res_local=True, eth_compressed=eth_c,
                          lane=((lane_base or 0) + si) if laned else None,
                          mode_label="NONE/ON_RECV/IMMEDIATE"))
    return moves


def expand_fused_recv_reduce(ctx: MoveContext, count: int, func: ReduceFunc,
                             src_rank: int, op0: int, dst: int, tag: int = 0,
                             compression: Compression = Compression.NONE,
                             laned: bool = True,
                             lane_base: int | None = None) -> list[Move]:
    """fused_recv_reduce (c:441-467): res = func(op0, incoming).

    Lane-tagged per segment: segment ``s`` reads op0 slice ``s`` and
    writes res slice ``s`` only, so lanes are pairwise disjoint and the
    combine of segment ``s`` overlaps the recv-match of ``s+1``
    (Move.lane invariant). Chained folds that read the previous fold's
    res as op0 (reduce_direct) are ordered lane-locally for free.
    ``laned=False`` marks documented barrier phases (log-depth vrank
    fold-in over the whole vector); ``lane_base`` offsets lane ids for
    global-chunk laning (see expand_send).
    """
    eth_c = bool(compression & Compression.ETH_COMPRESSED)
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size, eth_c)
    e0 = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    er = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    moves = []
    for si, (off, n) in enumerate(_segments(count, seg)):
        moves.append(Move(
            count=n,
            op0=Operand.imm(op0 + off * e0,
                            bool(compression & Compression.OP0_COMPRESSED)),
            op1=Operand.on_recv(src_rank, tag),
            res=Operand.imm(dst + off * er,
                            bool(compression & Compression.RES_COMPRESSED)),
            func=func, res_local=True, eth_compressed=eth_c,
            lane=((lane_base or 0) + si) if laned else None,
            mode_label="IMMEDIATE/ON_RECV/IMMEDIATE"))
    return moves


def expand_fused_recv_reduce_send(ctx: MoveContext, count: int,
                                  func: ReduceFunc, src_rank: int,
                                  dst_rank: int, op0: int, tag: int = 0,
                                  dst: int | None = None,
                                  compression: Compression = Compression.NONE,
                                  lane_base: int | None = None) -> list[Move]:
    """fused_recv_reduce_send (c:473-500): func(op0, incoming) -> peer
    (and optionally also to local dst — the RES_REMOTE|RES_LOCAL form used
    by allreduce phase 1, c:993-1023). Lane-tagged per segment like
    ``expand_fused_recv_reduce`` — the recv→combine→relay of segment ``s``
    forms one lane, so the relay of ``s-1`` streams out while ``s``
    combines and ``s+1`` recv-matches. ``lane_base`` offsets lane ids for
    the log-depth expansions' global-chunk laning (see expand_send)."""
    eth_c = bool(compression & Compression.ETH_COMPRESSED)
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size, eth_c)
    e0 = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    er = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    moves = []
    for si, (off, n) in enumerate(_segments(count, seg)):
        res = (Operand.imm(dst + off * er,
                           bool(compression & Compression.RES_COMPRESSED))
               if dst is not None else Operand.none())
        moves.append(Move(
            count=n,
            op0=Operand.imm(op0 + off * e0,
                            bool(compression & Compression.OP0_COMPRESSED)),
            op1=Operand.on_recv(src_rank, tag),
            res=res, func=func,
            res_remote=True, res_local=dst is not None,
            dst_rank=dst_rank, tag=tag, eth_compressed=eth_c,
            lane=(lane_base or 0) + si,
            mode_label="IMMEDIATE/ON_RECV/REMOTE(+LOCAL)"))
    return moves


# ---------------------------------------------------------------------------
# Collectives (parity: ccl_offload_control.c:502-1098)
# ---------------------------------------------------------------------------

def expand_broadcast(ctx: MoveContext, count: int, root: int, buf: int,
                     compression: Compression = Compression.NONE) -> list[Move]:
    """broadcast (c:507-571): root sends each segment to every peer
    (firmware: IMMEDIATE then MOVE_REPEAT to reuse the segment); non-root
    receives segments in order."""
    moves: list[Move] = []
    eth_c = bool(compression & Compression.ETH_COMPRESSED)
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size, eth_c)
    ebytes = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    if ctx.local_rank == root:
        # non-blocking: buf is never written by this program's later
        # moves; laned per segment so a caller that DID write buf earlier
        # (the non-fused allreduce reduces into it lane-by-lane) hands
        # each segment's fan-out a lane-local dependency on that write
        for si, (off, n) in enumerate(_segments(count, seg)):
            first = True
            for r in range(ctx.world_size):
                if r == root:
                    continue
                moves.append(Move(
                    count=n,
                    op0=Operand.imm(buf + off * ebytes,
                                    bool(compression & Compression.OP0_COMPRESSED)),
                    res_remote=True, dst_rank=r, tag=TAG_ANY,
                    eth_compressed=eth_c, blocking=False, lane=si,
                    mode_label="IMMEDIATE" if first else "REPEAT"))
                first = False
    else:
        moves += expand_recv(ctx, count, root, buf, tag=TAG_ANY,
                             compression=compression)
    return moves


def expand_broadcast_tree(ctx: MoveContext, count: int, root: int, buf: int,
                          compression: Compression = Compression.NONE
                          ) -> list[Move]:
    """broadcast, binomial tree: log2(W) rounds instead of the firmware's
    W-1 sequential sends (a TPU-native latency-optimal variant; the
    reference reserves the algorithm axis in xlnx-consts.hpp:43-66, and its
    2D-mesh analog is parallel/tree.py). Each rank receives once from its
    tree parent, then forwards to progressively nearer sub-roots."""
    W, me = ctx.world_size, ctx.local_rank
    if W == 1:
        return []
    vrank = (me - root) % W
    moves: list[Move] = []
    mask = 1
    while mask < W:
        if vrank & mask:
            parent = ((vrank ^ mask) + root) % W
            moves += expand_recv(ctx, count, parent, buf, tag=TAG_ANY,
                                 compression=compression)
            break
        mask <<= 1
    mask >>= 1
    while mask:
        if vrank + mask < W:
            child = ((vrank + mask) + root) % W
            # non-blocking: buf is never written after the (earlier) recv,
            # so forwards to all children may overlap each other; laned:
            # the forward of segment s reads only the slice the recv of
            # lane s wrote, so it chains behind that recv and streams out
            # while later segments are still arriving
            moves += expand_send(ctx, count, buf, child, tag=TAG_ANY,
                                 compression=compression, blocking=False,
                                 laned=True)
        mask >>= 1
    return moves


def expand_scatter(ctx: MoveContext, count: int, root: int, src: int,
                   dst: int,
                   compression: Compression = Compression.NONE) -> list[Move]:
    """scatter (c:575-627): root strided round-robin sends + local copy of
    its own chunk; non-root receives ``count`` elements. ``count`` is the
    per-rank chunk size (reference semantics)."""
    moves: list[Move] = []
    ebytes = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    if ctx.local_rank == root:
        for r in range(ctx.world_size):
            chunk = src + r * count * ebytes
            if r == root:
                moves += expand_copy(ctx, count, chunk, dst, compression)
                moves[-1].mode_label = "INCREMENT(local-copy)"
            else:
                # non-blocking: src chunks are read-only for the whole call
                sends = expand_send(ctx, count, chunk, r, tag=TAG_ANY,
                                    compression=compression, blocking=False)
                for m in sends:
                    m.mode_label = "INCREMENT(rr-send)"
                moves += sends
    else:
        moves += expand_recv(ctx, count, root, dst, tag=TAG_ANY,
                             compression=compression)
    return moves


def expand_gather_ring(ctx: MoveContext, count: int, root: int, src: int,
                       dst: int,
                       compression: Compression = Compression.NONE) -> list[Move]:
    """gather, ring algorithm (c:632-724): non-root sends its chunk to the
    previous ring neighbor toward root, then relays ``dist-1`` incoming
    chunks; root receives ``world_size-1`` chunks from its next neighbor
    into reverse-ring strided slots plus a local copy of its own."""
    W, me = ctx.world_size, ctx.local_rank
    ebytes = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    moves: list[Move] = []
    # distance from root along the ring (how many hops my data travels)
    dist = (me - root) % W
    prev_in_ring = (me + 1) % W   # data flows decreasing-rank toward root
    next_toward_root = (me - 1) % W
    if me == root:
        moves += expand_copy(ctx, count, src, dst + me * count * ebytes,
                             compression)
        for i in range(W - 1):
            # chunk arriving i-th belongs to rank (root+1+i) ... relayed in
            # arrival order from the next ring neighbor
            owner = (root + 1 + i) % W
            moves += expand_recv(ctx, count, prev_in_ring,
                                 dst + owner * count * ebytes, tag=TAG_ANY,
                                 compression=compression)
    else:
        # non-blocking: src is never written during a gather
        moves += expand_send(ctx, count, src, next_toward_root, tag=TAG_ANY,
                             compression=compression, blocking=False)
        # relay the chunks of the (W-1-dist) ranks farther from root
        relay_buf = dst  # non-root dst is scratch (reference reuses rx path)
        for _ in range(W - 1 - dist):
            moves += expand_recv(ctx, count, prev_in_ring, relay_buf,
                                 tag=TAG_ANY, compression=compression)
            # the relay reads the RES-typed scratch the recv just wrote —
            # and the NEXT recv overwrites that same scratch, so this send
            # must stay blocking (WAR hazard on relay_buf)
            moves += expand_send(ctx, count, relay_buf, next_toward_root,
                                 tag=TAG_ANY,
                                 compression=res_as_op0(compression))
    return moves


def expand_gather_direct(ctx: MoveContext, count: int, root: int, src: int,
                         dst: int,
                         compression: Compression = Compression.NONE
                         ) -> list[Move]:
    """gather, round-robin/direct (reference ``gather_rr``,
    xlnx-consts.hpp): every non-root sends its chunk straight to root;
    root receives W-1 strided chunks (pool matching absorbs arrival
    order) plus a local copy of its own."""
    W, me = ctx.world_size, ctx.local_rank
    ebytes = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    moves: list[Move] = []
    if me == root:
        moves += expand_copy(ctx, count, src, dst + me * count * ebytes,
                             compression)
        for r in range(W):
            if r == root:
                continue
            moves += expand_recv(ctx, count, r, dst + r * count * ebytes,
                                 tag=TAG_ANY, compression=compression)
    else:
        # non-blocking: the send is the non-root's whole program
        moves += expand_send(ctx, count, src, root, tag=TAG_ANY,
                             compression=compression, blocking=False)
    return moves


def expand_allgather_ring(ctx: MoveContext, count: int, src: int, dst: int,
                          compression: Compression = Compression.NONE
                          ) -> list[Move]:
    """allgather, ring (c:727-828): copy own chunk into its slot, send it to
    the next neighbor, then W-1 × {blocking recv into the originating
    rank's slot, relay onward}. The recv must retire before the relay reads
    the slot — the reference's explicit RAW-race note (c:788-791)."""
    W, me = ctx.world_size, ctx.local_rank
    ebytes = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    nxt, prv = (me + 1) % W, (me - 1) % W
    moves: list[Move] = []
    moves += expand_copy(ctx, count, src, dst + me * count * ebytes,
                         compression)
    # non-blocking: src is never written during an allgather, so the
    # initial send overlaps the first recv's pool wait; laned so segment
    # lanes align with the per-segment recv→relay chains below
    moves += expand_send(ctx, count, src, nxt, tag=TAG_ANY,
                         compression=compression, blocking=False,
                         laned=True)
    for i in range(W - 1):
        owner = (me - 1 - i) % W
        slot = dst + owner * count * ebytes
        rx = expand_recv(ctx, count, prv, slot, tag=TAG_ANY,
                         compression=compression)
        for m in rx:
            m.blocking = True  # RAW hazard vs the relay below (c:788-791)
        moves += rx
        if i < W - 2:
            # the relay reads the slot the recv just wrote, which is stored
            # in the RES dtype — substitute the flag like the firmware's
            # ETH/OP0 substitution when relaying from dst (c:739-743).
            # Non-blocking: each round's slot is written exactly once, so
            # the relay overlaps the NEXT round's recv (different slot) —
            # the ring-step overlap the pipelined executor exploits.
            # Laned: relay of segment s reads exactly the slice lane s's
            # recv wrote, so the RAW hazard is a lane-local edge and
            # sibling segments stream independently.
            moves += expand_send(ctx, count, slot, nxt, tag=TAG_ANY,
                                 compression=res_as_op0(compression),
                                 blocking=False, laned=True)
    return moves


def expand_allgather_direct(ctx: MoveContext, count: int, src: int, dst: int,
                            compression: Compression = Compression.NONE
                            ) -> list[Move]:
    """allgather, direct fan-out (round-robin): every rank eagerly sends
    its chunk to all peers, then receives W-1 chunks into their slots.
    One hop of latency vs the ring's W-1, at W× the injection rate."""
    W, me = ctx.world_size, ctx.local_rank
    ebytes = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    moves: list[Move] = []
    moves += expand_copy(ctx, count, src, dst + me * count * ebytes,
                         compression)
    for step in range(1, W):  # rotated schedule avoids hot receivers
        to = (me + step) % W
        # non-blocking: src is read-only; the recvs below write dst slots
        moves += expand_send(ctx, count, src, to, tag=TAG_ANY,
                             compression=compression, blocking=False)
    for step in range(1, W):
        frm = (me - step) % W
        moves += expand_recv(ctx, count, frm, dst + frm * count * ebytes,
                             tag=TAG_ANY, compression=compression)
    return moves


def expand_reduce_direct(ctx: MoveContext, count: int, root: int,
                         func: ReduceFunc, src: int, dst: int,
                         compression: Compression = Compression.NONE
                         ) -> list[Move]:
    """reduce, round-robin/direct (reference ``reduce_rr``): non-roots send
    straight to root; root folds arrivals into dst one sender at a time
    (first fold reads the root's own src as op0, later folds read dst)."""
    W, me = ctx.world_size, ctx.local_rank
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    moves: list[Move] = []
    if me != root:
        return expand_send(ctx, count, src, root, tag=TAG_ANY,
                           compression=compression)
    first = True
    for r in range(W):
        if r == root:
            continue
        # later folds read dst as op0, whose compressed-ness is the RES flag
        op0 = src if first else dst
        comp = compression if first else res_as_op0(compression)
        moves += expand_fused_recv_reduce(ctx, count, func, r, op0, dst,
                                          tag=TAG_ANY, compression=comp)
        first = False
    return moves


def expand_reduce_ring(ctx: MoveContext, count: int, root: int, func: ReduceFunc,
                       src: int, dst: int,
                       compression: Compression = Compression.NONE
                       ) -> list[Move]:
    """reduce, ring daisy chain (c:832-856): the rank after root plain-sends;
    middle ranks fused-recv-reduce-send; root fused-recv-reduces into dst."""
    W, me = ctx.world_size, ctx.local_rank
    nxt, prv = (me - 1) % W, (me + 1) % W  # data flows toward root
    moves: list[Move] = []
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    if (me - root) % W == W - 1:
        # farthest rank starts the chain; non-blocking: src is read-only
        # and this send is the rank's whole program (laned so downstream
        # per-segment fused chains see aligned lanes)
        moves += expand_send(ctx, count, src, nxt, tag=TAG_ANY,
                             compression=compression, blocking=False,
                             laned=True)
    elif me == root:
        moves += expand_fused_recv_reduce(ctx, count, func, prv, src, dst,
                                          tag=TAG_ANY, compression=compression)
    else:
        moves += expand_fused_recv_reduce_send(ctx, count, func, prv, nxt,
                                               src, tag=TAG_ANY,
                                               compression=compression)
    return moves


def expand_reduce_scatter_ring(ctx: MoveContext, count: int, func: ReduceFunc,
                               src: int, dst: int,
                               compression: Compression = Compression.NONE
                               ) -> list[Move]:
    """reduce_scatter, ring (c:860-939): send your (me+1)'th chunk, then for
    W-1 rounds fused recv+reduce+forward walking chunks backwards; the last
    round reduces into local dst (your own chunk). ``count`` is the
    per-rank chunk size."""
    W, me = ctx.world_size, ctx.local_rank
    ebytes = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    nxt, prv = (me - 1) % W, (me + 1) % W
    moves: list[Move] = []
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    first_chunk = (me + 1) % W
    # non-blocking: src chunks are read-only; the only local write of the
    # program is the final fused reduce into dst. Laned: the kickoff of
    # segment s feeds the downstream rank's lane-s fused chain.
    moves += expand_send(ctx, count, src + first_chunk * count * ebytes, nxt,
                         tag=TAG_ANY, compression=compression,
                         blocking=False, laned=True)
    for i in range(1, W):
        # flow is toward decreasing rank, so at round i the partial arriving
        # from prv=(me+1) is for chunk (me+1+i); the final round's chunk is
        # my own (me+W = me), saved locally — matching the reference's
        # "last iteration saves locally" (c:860-939).
        chunk = (me + 1 + i) % W
        op0 = src + chunk * count * ebytes
        if i < W - 1:
            moves += expand_fused_recv_reduce_send(
                ctx, count, func, prv, nxt, op0, tag=TAG_ANY,
                compression=compression)
        else:
            # final round: chunk == me; reduce into local dst
            moves += expand_fused_recv_reduce(
                ctx, count, func, prv, op0, dst, tag=TAG_ANY,
                compression=compression)
    return moves


def expand_allreduce_ring(ctx: MoveContext, count: int, func: ReduceFunc,
                          src: int, dst: int,
                          compression: Compression = Compression.NONE
                          ) -> list[Move]:
    """allreduce = fused ring reduce-scatter phase + ring allgather phase
    (c:942-1098). ``count`` is the *total* element count; chunking into W
    near-equal chunks with a bulk/tail split like the firmware
    (c:966-967)."""
    W, me = ctx.world_size, ctx.local_rank
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    # src chunks live in the OP0 dtype, dst chunks in the RES dtype — offsets
    # must be computed with each buffer's own element size (the firmware's
    # allreduce recomputes addresses per phase, c:966-979, 1031-1045)
    e_src = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    e_dst = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    bulk = count // W
    tail = count - bulk * (W - 1)  # last chunk absorbs the remainder

    def src_off(c: int) -> int:
        return src + c * bulk * e_src

    def dst_off(c: int) -> int:
        return dst + c * bulk * e_dst

    def chunk_len(c: int) -> int:
        return tail if c == W - 1 else bulk

    nxt, prv = (me - 1) % W, (me + 1) % W
    moves: list[Move] = []

    # --- phase 1: ring reduce-scatter over chunks (c:982-1023) ---
    # non-blocking: src chunks are read-only for the whole allreduce, so
    # the phase-1 kickoff send overlaps the first fused step's pool wait
    c0 = (me + 1) % W
    if chunk_len(c0):
        # laned: kickoff segment s is what the downstream lane-s fused
        # chain consumes first
        moves += expand_send(ctx, chunk_len(c0), src_off(c0), nxt,
                             tag=TAG_ANY, compression=compression,
                             blocking=False, laned=True)
    for i in range(1, W):
        c = (me + 1 + i) % W  # decreasing-rank flow: see reduce_scatter
        if not chunk_len(c):
            continue
        if i < W - 1:
            moves += expand_fused_recv_reduce_send(
                ctx, chunk_len(c), func, prv, nxt, src_off(c),
                tag=TAG_ANY, compression=compression)
        else:
            # c == me: own fully-reduced chunk lands in dst
            moves += expand_fused_recv_reduce(
                ctx, chunk_len(c), func, prv, src_off(c),
                dst_off(c), tag=TAG_ANY, compression=compression)

    # --- phase 2: ring allgather of reduced chunks from dst (c:1031-1095) ---
    # every phase-2 read sources the RES-typed dst buffer, so the OP0 flag is
    # substituted with the RES flag (the firmware reads dst with the RES
    # compression in its allgather phase, c:1031-1095)
    p2 = res_as_op0(compression)
    # non-blocking sends throughout phase 2: every dst slot is written
    # exactly once (own chunk by phase 1, each other chunk by its recv),
    # so a relay's source is never rewritten and the relay overlaps the
    # next round's recv — the per-step overlap the pipelined executor
    # turns into throughput (the serial engine pays send+recv in sequence)
    if chunk_len(me):
        # laned: the phase-2 kickoff of segment s reads the dst slice the
        # phase-1 final fused move of lane s wrote — same lane, so the
        # cross-phase RAW hazard is a lane-local edge and the kickoff of
        # segment s streams out while segment s+1 is still reducing
        moves += expand_send(ctx, chunk_len(me), dst_off(me), nxt,
                             tag=TAG_ANY, compression=p2, blocking=False,
                             laned=True)
    for i in range(1, W):
        c = (me + i) % W  # decreasing-rank flow: chunk me+i arrives at round i
        if not chunk_len(c):
            continue
        slot = dst_off(c)
        rx = expand_recv(ctx, chunk_len(c), prv, slot, tag=TAG_ANY,
                         compression=compression)
        for m in rx:
            m.blocking = True  # relay reads the slot next (c:1058-1061)
        moves += rx
        if i < W - 1:
            # laned: relay of segment s reads exactly what lane s's recv
            # wrote (slot written once per round), sibling lanes disjoint
            moves += expand_send(ctx, chunk_len(c), slot, nxt, tag=TAG_ANY,
                                 compression=p2, blocking=False, laned=True)
    return moves


def expand_allreduce_nonfused(ctx: MoveContext, count: int, func: ReduceFunc,
                              src: int, dst: int,
                              compression: Compression = Compression.NONE
                              ) -> list[Move]:
    """allreduce, non-fused (the reference's sw-orchestrated variant axis,
    xlnx-consts.hpp:43-66): ring reduce to rank 0, then broadcast of dst.
    2(W-1) serial hops vs the fused ring's bandwidth-optimal schedule —
    kept as a selectable algorithm for small messages and for diffing."""
    moves = expand_reduce_ring(ctx, count, 0, func, src, dst, compression)
    # the bcast reads/writes dst, whose compressed-ness is RES_COMPRESSED;
    # bcast addresses its buffer via the OP0 flag
    moves += expand_broadcast(ctx, count, 0, dst, res_as_op0(compression))
    return moves


# ---------------------------------------------------------------------------
# Log-depth family: recursive doubling/halving + binomial trees
# (TPU-native latency-optimal variants; the reference reserves the
# algorithm axis in xlnx-consts.hpp:43-66 — ring/rr are its only
# firmware expansions. ACCL+ [arXiv:2312.11742] shows algorithm choice
# dominating in the small-message regime these target.)
#
# Shared conventions:
#   * Non-power-of-2 worlds fold to p = 2^floor(log2 W) vranks: the first
#     2r ranks (r = W - p) pair up {even participant, odd extra}; extras
#     contribute their data in a PRE phase and receive their result in a
#     POST phase. Fold-phase moves are documented BARRIERS (blocking,
#     lane=None): their whole-vector transfers span regions written by
#     many lanes, so no single lane edge can order them.
#   * Pairwise exchange rounds are laned per GLOBAL chunk: every move
#     touching chunk c, wire segment s carries lane c*S + s (S = wire
#     segments per chunk), so the reader of chunk c in round k+1 chains
#     behind the writer of chunk c in round k (a lane-local RAW edge the
#     streamed executor pipelines), while sibling chunks/segments — whose
#     bytes are disjoint — stream concurrently (Move.lane invariant).
#   * No scratch region is ever REUSED for two different payloads (the
#     gather-ring relay hazard class): each chunk slot is written exactly
#     once per program, which is what makes the laned non-blocking
#     relays legal.
# ---------------------------------------------------------------------------

def _vrank_fold(world: int, rank: int) -> tuple[int, int, int | None]:
    """(p, r, vrank) of the standard 2^floor(log2 W) fold: p participants,
    r = W - p extras. Ranks below 2r pair up — even ranks participate as
    vrank rank/2 carrying their odd neighbor's data; odd ranks are extras
    (vrank None). Ranks at/above 2r participate as vrank rank - r."""
    p = 1 << (world.bit_length() - 1)
    r = world - p
    if rank < 2 * r:
        return p, r, rank // 2 if rank % 2 == 0 else None
    return p, r, rank - r


def _vrank_to_rank(v: int, r: int) -> int:
    """Inverse of the fold's vrank assignment."""
    return 2 * v if v < r else v + r


def _vchunks(v: int, r: int) -> tuple[int, ...]:
    """Real chunk indices vrank ``v`` represents: its own rank's chunk
    plus — for paired participants — the extra neighbor's. Ascending, and
    contiguous across ascending vranks (the fold preserves rank order)."""
    return (2 * v, 2 * v + 1) if v < r else (v + r,)


def _block_chunks(base: int, n: int, r: int) -> list[int]:
    """Chunks represented by the vrank block [base, base+n) — the unit
    recursive doubling/halving exchanges. Sorted ascending on both sides
    of a pairwise exchange, so per-peer wire order (and therefore seqn
    matching) agrees between partners by construction."""
    return [c for u in range(base, base + n) for c in _vchunks(u, r)]


def _chunk_span(base: int, n: int, r: int) -> tuple[int, int]:
    """[lo, hi) real-chunk range of the vrank block [base, base+n) — the
    fold preserves rank order, so a vrank block's chunks are CONTIGUOUS.
    This is what lets the latency-regime transfer mode below ship a
    whole block as one wire message."""
    lo = 2 * base if base < r else base + r
    last = base + n - 1
    return lo, (2 * last + 1 if last < r else last + r) + 1


# Two transfer granularities per exchange round (selected identically on
# every rank from world/count/segment-size, so wire order agrees):
#   * BLOCK mode — the whole working vector fits ONE wire segment (the
#     alpha-dominated regime the family exists for): each round moves its
#     contiguous chunk block as a single message, so a rank pays
#     ceil(log2 W) messages total instead of the ring's W-1. All block
#     moves ride ONE lane (lane 0): the send of round k+1 reads bytes
#     round k's recv wrote, and the shared lane chain IS that RAW edge —
#     cross-round segment pipelining cannot exist at one segment anyway.
#   * CHUNK mode — otherwise: per-chunk messages with global-chunk lanes
#     (lane = c*S + s), so the streamed executor pipelines segments of
#     independent chunks across rounds.
def _block_xfer(ctx: MoveContext, total_count: int,
                compression: Compression) -> bool:
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size,
                     bool(compression & Compression.ETH_COMPRESSED))
    return total_count <= seg


def _chunk_lanes(ctx: MoveContext, count: int,
                 compression: Compression) -> int:
    """Wire segments per chunk — the global-chunk lane stride. Constant
    across rounds (segmentation depends only on the wire element size),
    so lane c*S + s names the same bytes of chunk c in every round."""
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size,
                     bool(compression & Compression.ETH_COMPRESSED))
    return max(1, -(-count // seg))


def expand_allgather_recursive_doubling(ctx: MoveContext, count: int,
                                        src: int, dst: int,
                                        compression: Compression =
                                        Compression.NONE) -> list[Move]:
    """allgather, recursive doubling: ceil(log2 W) pairwise exchange
    rounds instead of the ring's W-1 dependency hops; round k swaps the
    2^k chunks each side has accumulated. ``count`` is the per-rank
    chunk size. Non-power-of-2 worlds fold (module comment above)."""
    W, me = ctx.world_size, ctx.local_rank
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    e_dst = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    p, r, v = _vrank_fold(W, me)
    S = _chunk_lanes(ctx, count, compression)
    p2 = res_as_op0(compression)
    moves: list[Move] = []
    if v is None:
        partner = me - 1
        # fold-in barrier: contribute my chunk (default blocking send —
        # fold phases are documented barriers, not pipelined lanes)
        moves += expand_send(ctx, count, src, partner, tag=TAG_ANY,
                             compression=compression)
        # fold-out barrier: the whole gathered vector lands in dst
        moves += expand_recv(ctx, W * count, partner, dst, tag=TAG_ANY,
                             compression=compression, laned=False)
        return moves
    moves += expand_copy(ctx, count, src, dst + me * count * e_dst,
                         compression)
    if me < 2 * r:
        # fold-in barrier: adopt the extra partner's chunk into its slot
        moves += expand_recv(ctx, count, me + 1,
                             dst + (me + 1) * count * e_dst, tag=TAG_ANY,
                             compression=compression, laned=False)
    block = _block_xfer(ctx, W * count, compression)
    mask = 1
    while mask < p:
        pv = v ^ mask
        partner = _vrank_to_rank(pv, r)
        if block:
            mlo, mhi = _chunk_span(v & ~(mask - 1), mask, r)
            tlo, thi = _chunk_span(pv & ~(mask - 1), mask, r)
            # one message per round: the whole owned block from dst (own
            # chunk was copied there up front; every slot is written
            # once, and later recvs only write blocks I don't own yet —
            # never this source). Single shared lane: the chain orders
            # this send behind the previous round's recv (a lane-local
            # RAW edge).
            moves += expand_send(ctx, (mhi - mlo) * count,
                                 dst + mlo * count * e_dst, partner,
                                 tag=TAG_ANY, compression=p2,
                                 blocking=False, laned=True)
            moves += expand_recv(ctx, (thi - tlo) * count, partner,
                                 dst + tlo * count * e_dst, tag=TAG_ANY,
                                 compression=compression)
            mask <<= 1
            continue
        mine = _block_chunks(v & ~(mask - 1), mask, r)
        theirs = _block_chunks(pv & ~(mask - 1), mask, r)
        for c in mine:
            if c == me:
                # own chunk straight from src: read-only the whole call
                moves += expand_send(ctx, count, src, partner, tag=TAG_ANY,
                                     compression=compression,
                                     blocking=False, lane_base=c * S)
            else:
                # relay of an accumulated chunk: its dst slot is written
                # exactly once (fold-in barrier or this chunk's lane
                # recvs), so the RAW hazard is a lane-local edge and the
                # send overlaps sibling chunks' recvs. Reads dst, which
                # is RES-typed — substitute the flag like the firmware's
                # relay-from-dst (c:739-743).
                moves += expand_send(ctx, count, dst + c * count * e_dst,
                                     partner, tag=TAG_ANY, compression=p2,
                                     blocking=False, lane_base=c * S)
        for c in theirs:
            moves += expand_recv(ctx, count, partner,
                                 dst + c * count * e_dst, tag=TAG_ANY,
                                 compression=compression, lane_base=c * S)
        mask <<= 1
    if me < 2 * r:
        # fold-out barrier: ship the whole gathered vector to the extra
        # (reads every chunk slot — spans all lanes, so it must drain)
        moves += expand_send(ctx, W * count, dst, me + 1, tag=TAG_ANY,
                             compression=p2)
    return moves


def expand_reduce_scatter_recursive_halving(
        ctx: MoveContext, count: int, func: ReduceFunc, src: int, dst: int,
        scratch: int, compression: Compression = Compression.NONE
        ) -> list[Move]:
    """reduce_scatter, recursive halving: ceil(log2 W) rounds, each
    exchanging partials for the half of the active chunk range the
    partner's sub-block owns. ``count`` is the per-rank chunk size.

    ``scratch`` (the descriptor's addr_1, driver-plumbed) must hold
    ``W*count`` elements in the UNCOMPRESSED dtype: the working vector of
    partial sums. Each scratch chunk is written once per round it stays
    active (always by its own global-chunk lane), never reused for a
    different payload."""
    W, me = ctx.world_size, ctx.local_rank
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    if not scratch:
        raise ValueError(
            "reduce_scatter RECURSIVE_DOUBLING requires a scratch buffer "
            "of world_size*count uncompressed elements in addr_1 (the "
            "ACCL driver allocates and plumbs one automatically)")
    p, r, v = _vrank_fold(W, me)
    S = _chunk_lanes(ctx, count, compression)
    e_src = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    e_u = ctx.ebytes(False)                    # scratch is uncompressed
    eth = compression & Compression.ETH_COMPRESSED
    moves: list[Move] = []
    if v is None:
        partner = me - 1
        # fold-in barrier: contribute the whole vector
        moves += expand_send(ctx, W * count, src, partner, tag=TAG_ANY,
                             compression=compression)
        # fold-out barrier: my fully-reduced chunk
        moves += expand_recv(ctx, count, partner, dst, tag=TAG_ANY,
                             compression=compression, laned=False)
        return moves
    in_scratch: set[int] = set()
    if me < 2 * r:
        # fold-in barrier: reduce the extra's whole vector into scratch
        # (op0 = src; scratch result is uncompressed, so RES clears)
        moves += expand_fused_recv_reduce(
            ctx, W * count, func, me + 1, src, scratch, tag=TAG_ANY,
            compression=compression & ~Compression.RES_COMPRESSED,
            laned=False)
        in_scratch = set(range(W))
    block = _block_xfer(ctx, W * count, compression)
    half = p >> 1
    while half:
        pv = v ^ half
        partner = _vrank_to_rank(pv, r)
        if block:
            klo, khi = _chunk_span(v & ~(half - 1), half, r)
            glo, ghi = _chunk_span(pv & ~(half - 1), half, r)
            folded = bool(in_scratch)   # round-1 partials may still be src
            # one message per round: partials for the partner's whole
            # contiguous block. Sources are src (read-only) or scratch
            # regions written exactly once by the previous round's fused
            # move on this same single lane (the lane chain is the RAW
            # edge); the give block leaves the active range, never
            # written again.
            moves += expand_send(
                ctx, (ghi - glo) * count,
                (scratch + glo * count * e_u if folded
                 else src + glo * count * e_src),
                partner, tag=TAG_ANY,
                compression=eth if folded else compression,
                blocking=False, laned=True)
            moves += expand_fused_recv_reduce(
                ctx, (khi - klo) * count, func, partner,
                (scratch + klo * count * e_u if folded
                 else src + klo * count * e_src),
                scratch + klo * count * e_u, tag=TAG_ANY,
                compression=(eth if folded
                             else compression
                             & ~Compression.RES_COMPRESSED))
            in_scratch.update(range(klo, khi))
            half >>= 1
            continue
        keep = _block_chunks(v & ~(half - 1), half, r)
        give = _block_chunks(pv & ~(half - 1), half, r)
        for c in give:
            if c in in_scratch:
                # partials for the partner's half: the scratch chunk was
                # written exactly once since (by lane c*S moves — a
                # lane-local edge) and never again (it leaves the active
                # range), so the send is non-blocking
                moves += expand_send(ctx, count, scratch + c * count * e_u,
                                     partner, tag=TAG_ANY, compression=eth,
                                     blocking=False, lane_base=c * S)
            else:
                # first round, no fold: partials ARE src — read-only
                moves += expand_send(ctx, count, src + c * count * e_src,
                                     partner, tag=TAG_ANY,
                                     compression=compression,
                                     blocking=False, lane_base=c * S)
        for c in keep:
            op0 = (scratch + c * count * e_u if c in in_scratch
                   else src + c * count * e_src)
            comp = (eth if c in in_scratch
                    else compression & ~Compression.RES_COMPRESSED)
            moves += expand_fused_recv_reduce(
                ctx, count, func, partner, op0, scratch + c * count * e_u,
                tag=TAG_ANY, compression=comp, lane_base=c * S)
        in_scratch.update(keep)
        half >>= 1
    # epilogue: my chunk lands in dst (local copy — scratch is
    # uncompressed, dst carries the call's RES compression)
    moves += expand_copy(ctx, count, scratch + me * count * e_u, dst,
                         compression & Compression.RES_COMPRESSED)
    if me < 2 * r:
        # fold-out barrier: the extra's fully-reduced chunk
        moves += expand_send(ctx, count, scratch + (me + 1) * count * e_u,
                             me + 1, tag=TAG_ANY, compression=eth)
    return moves


def expand_allreduce_rd(ctx: MoveContext, count: int, func: ReduceFunc,
                        src: int, dst: int,
                        compression: Compression = Compression.NONE
                        ) -> list[Move]:
    """allreduce, Rabenseifner: recursive-halving reduce-scatter followed
    by recursive-doubling allgather — 2*ceil(log2 W) dependency rounds
    against the fused ring's 2(W-1), at the same ~2n(W-1)/W wire volume.
    ``count`` is the TOTAL element count, chunked with the ring
    expansion's bulk/tail split (c:966-967); ``dst`` doubles as the
    working vector for the halving phase, so no scratch is needed."""
    W, me = ctx.world_size, ctx.local_rank
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    p, r, v = _vrank_fold(W, me)
    e_src = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    e_dst = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    bulk = count // W
    tail = count - bulk * (W - 1)   # last chunk absorbs the remainder

    def c_off(c: int) -> int:
        return c * bulk

    def c_len(c: int) -> int:
        return tail if c == W - 1 else bulk

    S = _chunk_lanes(ctx, tail, compression)  # tail >= bulk bounds lanes
    p2 = res_as_op0(compression)
    moves: list[Move] = []
    if v is None:
        partner = me - 1
        # fold-in barrier: whole input vector
        moves += expand_send(ctx, count, src, partner, tag=TAG_ANY,
                             compression=compression)
        # fold-out barrier: whole reduced vector
        moves += expand_recv(ctx, count, partner, dst, tag=TAG_ANY,
                             compression=compression, laned=False)
        return moves
    in_dst: set[int] = set()
    if me < 2 * r:
        # fold-in barrier: reduce the extra's whole vector into dst
        moves += expand_fused_recv_reduce(ctx, count, func, me + 1, src,
                                          dst, tag=TAG_ANY,
                                          compression=compression,
                                          laned=False)
        in_dst = set(range(W))
    def span(lo: int, hi: int) -> tuple[int, int]:
        """Chunk range [lo, hi) -> (element offset, element count)."""
        if lo >= hi:
            return 0, 0
        return c_off(lo), c_off(hi - 1) + c_len(hi - 1) - c_off(lo)

    block = _block_xfer(ctx, count, compression)
    # --- phase 1: recursive-halving reduce-scatter over dst ---
    half = p >> 1
    while half:
        pv = v ^ half
        partner = _vrank_to_rank(pv, r)
        if block:
            goff, gn = span(*_chunk_span(pv & ~(half - 1), half, r))
            koff, kn = span(*_chunk_span(v & ~(half - 1), half, r))
            folded = bool(in_dst)   # round-1 partials may still be src
            # one message per round (see _block_xfer): partials for the
            # partner's contiguous half, from src (read-only) or from
            # dst written exactly once by the previous round's fused
            # move on this same single lane; the give half leaves the
            # active range and is untouched until phase 2's recv, which
            # the shared lane chain orders behind this send
            if gn:
                moves += expand_send(
                    ctx, gn, (dst + goff * e_dst if folded
                              else src + goff * e_src),
                    partner, tag=TAG_ANY,
                    compression=p2 if folded else compression,
                    blocking=False, laned=True)
            if kn:
                op0 = (dst + koff * e_dst if folded
                       else src + koff * e_src)
                comp = p2 if folded else compression
                if half == 1:
                    # the last halving partner IS the first doubling
                    # partner (v^1): fuse the final reduce with the
                    # phase-2 kickoff — result lands in dst AND ships to
                    # the partner in one move, saving a dependency round
                    # (the firmware's RES_REMOTE|RES_LOCAL form,
                    # c:993-1023). Phase 2's mask=1 send is skipped.
                    moves += expand_fused_recv_reduce_send(
                        ctx, kn, func, partner, partner, op0,
                        tag=TAG_ANY, dst=dst + koff * e_dst,
                        compression=comp)
                else:
                    moves += expand_fused_recv_reduce(
                        ctx, kn, func, partner, op0, dst + koff * e_dst,
                        tag=TAG_ANY, compression=comp)
            in_dst.update(range(W))
            half >>= 1
            continue
        keep = _block_chunks(v & ~(half - 1), half, r)
        give = _block_chunks(pv & ~(half - 1), half, r)
        for c in give:
            if not c_len(c):
                continue
            if c in in_dst:
                # partials of the partner's half, accumulated in dst:
                # written exactly once since by this chunk's lane (a
                # lane-local edge), never written again — non-blocking
                moves += expand_send(ctx, c_len(c),
                                     dst + c_off(c) * e_dst, partner,
                                     tag=TAG_ANY, compression=p2,
                                     blocking=False, lane_base=c * S)
            else:
                # first round without a fold: partials ARE src (read-only)
                moves += expand_send(ctx, c_len(c),
                                     src + c_off(c) * e_src, partner,
                                     tag=TAG_ANY, compression=compression,
                                     blocking=False, lane_base=c * S)
        for c in keep:
            if not c_len(c):
                continue
            op0 = (dst + c_off(c) * e_dst if c in in_dst
                   else src + c_off(c) * e_src)
            comp = p2 if c in in_dst else compression
            if half == 1:
                # last halving partner == first doubling partner (v^1):
                # fuse the final reduce with the phase-2 kickoff (see
                # the block-mode comment above)
                moves += expand_fused_recv_reduce_send(
                    ctx, c_len(c), func, partner, partner, op0,
                    tag=TAG_ANY, dst=dst + c_off(c) * e_dst,
                    compression=comp, lane_base=c * S)
            else:
                moves += expand_fused_recv_reduce(
                    ctx, c_len(c), func, partner, op0,
                    dst + c_off(c) * e_dst, tag=TAG_ANY,
                    compression=comp, lane_base=c * S)
        in_dst.update(keep)
        half >>= 1
    # --- phase 2: recursive-doubling allgather over dst ---
    mask = 1
    while mask < p:
        pv = v ^ mask
        partner = _vrank_to_rank(pv, r)
        if block:
            moff, mn = span(*_chunk_span(v & ~(mask - 1), mask, r))
            toff, tn = span(*_chunk_span(pv & ~(mask - 1), mask, r))
            # one message per round: my finalized contiguous block (each
            # byte written exactly once — phase-1 fused move or an
            # earlier phase-2 recv on this same single lane, which
            # orders the relay behind it). The mask=1 send already left
            # with the fused phase-1 kickoff.
            if mn and mask != 1:
                moves += expand_send(ctx, mn, dst + moff * e_dst, partner,
                                     tag=TAG_ANY, compression=p2,
                                     blocking=False, laned=True)
            if tn:
                moves += expand_recv(ctx, tn, partner, dst + toff * e_dst,
                                     tag=TAG_ANY, compression=compression)
            mask <<= 1
            continue
        mine = _block_chunks(v & ~(mask - 1), mask, r)
        theirs = _block_chunks(pv & ~(mask - 1), mask, r)
        for c in mine:
            if not c_len(c) or mask == 1:
                # mask=1 sends already left with the fused phase-1 kickoff
                continue
            # each dst chunk was finalized exactly once (phase-1 fused
            # move or a phase-2 recv, both on lane c*S) and is never
            # written again — the relay is a lane-local edge
            moves += expand_send(ctx, c_len(c), dst + c_off(c) * e_dst,
                                 partner, tag=TAG_ANY, compression=p2,
                                 blocking=False, lane_base=c * S)
        for c in theirs:
            if not c_len(c):
                continue
            moves += expand_recv(ctx, c_len(c), partner,
                                 dst + c_off(c) * e_dst, tag=TAG_ANY,
                                 compression=compression, lane_base=c * S)
        mask <<= 1
    if me < 2 * r:
        # fold-out barrier: whole reduced vector to the extra
        moves += expand_send(ctx, count, dst, me + 1, tag=TAG_ANY,
                             compression=p2)
    return moves


def expand_reduce_tree(ctx: MoveContext, count: int, root: int,
                       func: ReduceFunc, src: int, dst: int,
                       compression: Compression = Compression.NONE
                       ) -> list[Move]:
    """reduce, binomial tree: ceil(log2 W) dependency rounds (vs the
    daisy chain's W-1), with the fold work spread across internal nodes
    instead of serialized at one endpoint (reduce_direct's root). Works
    for any W directly — no vrank fold needed.

    Non-root internal nodes accumulate into ``dst`` used as an n-element
    scratch (the gather-ring convention: non-root dst is scratch; the
    ACCL driver allocates one when the caller passes none)."""
    W, me = ctx.world_size, ctx.local_rank
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    v = (me - root) % W
    moves: list[Move] = []
    first = True
    mask = 1
    while mask < W:
        if v & mask:
            parent = ((v ^ mask) + root) % W
            if first:
                # leaf: src is read-only and this send is the whole
                # program — laned so the parent-side fused chain sees
                # aligned per-segment lanes
                moves += expand_send(ctx, count, src, parent, tag=TAG_ANY,
                                     compression=compression,
                                     blocking=False, laned=True)
            else:
                # internal node: the accumulator is complete — every
                # child fold wrote segment s via lane s (lane-local RAW
                # edges) and nothing writes it after this send
                moves += expand_send(ctx, count, dst, parent, tag=TAG_ANY,
                                     compression=res_as_op0(compression),
                                     blocking=False, laned=True)
            break
        child_v = v + mask
        if child_v < W:
            if not dst:
                raise ValueError(
                    "reduce TREE requires an accumulator buffer on "
                    "internal ranks (non-root dst is scratch; the ACCL "
                    "driver allocates one automatically)")
            op0 = src if first else dst
            comp = compression if first else res_as_op0(compression)
            moves += expand_fused_recv_reduce(
                ctx, count, func, (child_v + root) % W, op0, dst,
                tag=TAG_ANY, compression=comp)
            first = False
        mask <<= 1
    return moves


def expand_gather_tree(ctx: MoveContext, count: int, root: int, src: int,
                       dst: int,
                       compression: Compression = Compression.NONE
                       ) -> list[Move]:
    """gather, binomial tree: each rank receives its children's subtree
    chunks, then forwards its whole subtree to its parent — ceil(log2 W)
    dependency rounds vs the ring's W-1 relay hops, without the direct
    algorithm's W-1 payload incast at root. Any W works directly.

    Non-root ``dst`` is a subtree scratch holding
    ``min(lowest_set_bit(vrank), W - vrank) - 1`` chunks in vrank order
    (each written exactly once — never the ring's reused relay slot);
    the driver sizes it via ``tree_gather_scratch_chunks``. Root lands
    chunks straight into their owners' dst slots."""
    W, me = ctx.world_size, ctx.local_rank
    e = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    v = (me - root) % W
    S = _chunk_lanes(ctx, count, compression)
    moves: list[Move] = []
    if me == root:
        moves += expand_copy(ctx, count, src, dst + root * count * e,
                             compression)

    def slot(u: int) -> int:
        """Landing address of vrank u's chunk at this rank: the owner's
        dst slot at root, the (u - v - 1)-th scratch slot elsewhere."""
        if me == root:
            return dst + ((u + root) % W) * count * e
        return dst + (u - v - 1) * count * e

    mask = 1
    while mask < W:
        if v & mask:
            parent = ((v ^ mask) + root) % W
            # own chunk first (src is read-only for the whole call),
            # then the received subtree in vrank order
            moves += expand_send(ctx, count, src, parent, tag=TAG_ANY,
                                 compression=compression, blocking=False,
                                 lane_base=((v + root) % W) * S)
            for u in range(v + 1, min(v + mask, W)):
                # relay of vrank u's chunk: its scratch slot was written
                # exactly once, by this chunk's own lane recvs — a
                # lane-local edge, so the forward is non-blocking
                moves += expand_send(ctx, count, slot(u), parent,
                                     tag=TAG_ANY,
                                     compression=res_as_op0(compression),
                                     blocking=False,
                                     lane_base=((u + root) % W) * S)
            break
        child = ((v + mask) + root) % W
        for u in range(v + mask, min(v + 2 * mask, W)):
            moves += expand_recv(ctx, count, child, slot(u), tag=TAG_ANY,
                                 compression=compression,
                                 lane_base=((u + root) % W) * S)
        mask <<= 1
    return moves


def tree_gather_scratch_chunks(world: int, rank: int, root: int) -> int:
    """Chunks a non-root rank's TREE-gather scratch must hold (its
    received subtree). The driver uses this to size the buffer it
    substitutes when the caller passes none."""
    v = (rank - root) % world
    lsb = v & -v
    return max(0, min(lsb, world - v) - 1)


def expand_alltoall(ctx: MoveContext, count: int, src: int, dst: int,
                    compression: Compression = Compression.NONE) -> list[Move]:
    """alltoall (capability extension; the reference reserves the op in its
    XRT enums): rank r sends chunk d to rank d and receives chunk s from
    every s. ``count`` is the per-pair chunk size."""
    W, me = ctx.world_size, ctx.local_rank
    # src chunks are OP0-typed, dst chunks RES-typed — separate element sizes
    e_src = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    e_dst = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    S = _chunk_lanes(ctx, count, compression)
    moves: list[Move] = []
    # self-exchange: a LANED local copy on chunk ``me``'s global lane
    # instead of a barrier — no other move of the program touches chunk
    # me (sends read chunks (me+s)%W, recvs write chunks (me-t)%W, s,t >=
    # 1), so the lane carries no concurrent toucher and the whole program
    # joins the streamed pipeline (the barrier used to drain every lane
    # before the first remote byte moved)
    self_mv = expand_copy(ctx, count, src + me * count * e_src,
                          dst + me * count * e_dst, compression)
    for m in self_mv:
        m.lane = me * S
    moves += self_mv
    # round-robin schedule on GLOBAL-CHUNK lanes (lane = chunk * S + seg,
    # the log-depth convention): step s sends chunk (me+s) and step t
    # recvs chunk (me-t), which collide IN-PLACE (src aliasing dst)
    # exactly when t == W-s — both moves then carry the same chunk's
    # lanes, so the hazard is an explicit lane-local edge (the later move
    # chains behind the earlier, preserving serial program order per
    # chunk) instead of the blocking barrier the first half of the
    # schedule used to pay. Sends are therefore non-blocking throughout:
    # the only later writer of a send's source is its own lane's recv
    # (Move.blocking lane-local exception).
    for step in range(1, W):
        to = (me + step) % W
        frm = (me - step) % W
        moves += expand_send(ctx, count, src + to * count * e_src, to,
                             tag=TAG_ANY, compression=compression,
                             blocking=False, lane_base=to * S)
        moves += expand_recv(ctx, count, frm, dst + frm * count * e_dst,
                             tag=TAG_ANY, compression=compression,
                             lane_base=frm * S)
    return moves


def expand_alltoallv(ctx: MoveContext, send_counts, recv_counts,
                     src: int, dst: int,
                     compression: Compression = Compression.NONE
                     ) -> list[Move]:
    """Variable-count all-to-all (MPI_Alltoallv shape): rank r sends
    ``send_counts[d]`` elements to rank d from the d-th send interval and
    receives ``recv_counts[s]`` elements from rank s into the s-th recv
    interval; intervals are the prefix-sum tilings of the two count
    vectors (the MPI contiguous-displacement special case — the only
    layout the uneven-reshard fast path needs, and the one a wire count
    vector can describe without a displacement vector).

    Laning follows :func:`expand_alltoall`'s global-chunk convention —
    lane = peer * S + seg — except S derives from the MAX per-peer count,
    so the widest chunk's segments still get distinct lanes and no two
    peers' lanes collide. Zero-count peers contribute no moves at all
    (skewed MoE routing routinely zeroes most of the vector). Sends stay
    non-blocking: no later move writes a send's source interval — recvs
    write ``dst``, and the engine never sees ``src`` alias ``dst`` (the
    DRIVER stages overlapping/in-place exchanges through scratch, because
    uneven intervals can alias across DIFFERENT peers' chunks, which no
    lane-local edge can order).
    """
    W, me = ctx.world_size, ctx.local_rank
    if len(send_counts) != W or len(recv_counts) != W:
        raise ValueError(
            f"alltoallv count vectors must have world_size={W} entries; "
            f"got {len(send_counts)} send / {len(recv_counts)} recv")
    if min(send_counts, default=0) < 0 or min(recv_counts, default=0) < 0:
        raise ValueError("alltoallv counts must be non-negative")
    e_src = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    e_dst = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    cmax = max(max(send_counts), max(recv_counts))
    S = _chunk_lanes(ctx, cmax, compression)
    # prefix sums: element offset of peer j's interval on each side
    soff = [0] * (W + 1)
    doff = [0] * (W + 1)
    for j in range(W):
        soff[j + 1] = soff[j] + int(send_counts[j])
        doff[j + 1] = doff[j] + int(recv_counts[j])
    moves: list[Move] = []
    # self-exchange: laned local copy on peer ``me``'s lane block (same
    # no-barrier rationale as expand_alltoall — nothing else touches the
    # me-interval on either side)
    if send_counts[me]:
        self_mv = expand_copy(ctx, int(send_counts[me]),
                              src + soff[me] * e_src,
                              dst + doff[me] * e_dst, compression)
        for m in self_mv:
            m.lane = me * S
        moves += self_mv
    # round-robin step schedule (step s: send to me+s, recv from me-s) so
    # uneven exchanges pipeline like the fixed-size alltoall: every rank
    # pairs sender/receiver the same step, and per-peer lane blocks let
    # the streamed executor interleave segments of different peers
    for step in range(1, W):
        to = (me + step) % W
        frm = (me - step) % W
        if send_counts[to]:
            # non-rewritten source (Move.blocking): sends read src only,
            # recvs write dst only, and the driver guarantees src never
            # aliases dst (in-place exchanges are staged through scratch)
            moves += expand_send(ctx, int(send_counts[to]),
                                 src + soff[to] * e_src, to,
                                 tag=TAG_ANY, compression=compression,
                                 blocking=False, lane_base=to * S)
        if recv_counts[frm]:
            moves += expand_recv(ctx, int(recv_counts[frm]), frm,
                                 dst + doff[frm] * e_dst,
                                 tag=TAG_ANY, compression=compression,
                                 lane_base=frm * S)
    return moves


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

def resolve_algorithm(scenario: CCLOp, algorithm, *, world_size: int,
                      count: int, elem_bytes: int, tuner: Any = None,
                      addr_1: int = 0) -> CollectiveAlgorithm:
    """The concrete algorithm ``expand_call`` will expand for a descriptor.

    Mirrors the ``pick`` resolution inside :func:`expand_call` — AUTO goes
    through the tuner (size/topology-aware) and falls back to the shared
    ``DEFAULT_ALGORITHMS`` table, including the reduce_scatter
    no-scratch-buffer fallback to RING. The compiled-plan cache keys
    entries on this value, so a tuner re-resolution (epsilon-greedy
    exploration, EWMA switching) lands on a DIFFERENT cache key and can
    never be served a stale plan expanded for the old algorithm. An
    explicit selector passes through unchanged (expansion-level errors,
    e.g. RECURSIVE_DOUBLING reduce_scatter without scratch, still fail
    loudly there)."""
    A = CollectiveAlgorithm
    alg = A(algorithm)
    valid = VALID_ALGORITHMS.get(scenario.name)
    if valid is None or alg != A.AUTO:
        return alg
    chosen = A.AUTO
    if tuner is not None:
        chosen = A(tuner.select(scenario.name, world_size,
                                count * elem_bytes))
    if chosen == A.AUTO or chosen == A.HIERARCHICAL \
            or chosen not in valid:
        # HIERARCHICAL is a driver-level phase program (accl_tpu/hier):
        # a descriptor that reached the ENGINE still carrying AUTO is by
        # definition a flat single-communicator call, so a tuner leaning
        # hierarchical falls back to the flat default here — same as
        # expand_call's pick() table omission.
        chosen = DEFAULT_ALGORITHMS[scenario.name]
    if (scenario == CCLOp.reduce_scatter
            and chosen == A.RECURSIVE_DOUBLING and not addr_1):
        # an engine-level AUTO resolution without the driver-plumbed
        # scratch (addr_1) must fall back to RING, exactly like
        # expand_call's table omission does
        chosen = A.RING
    return chosen


def expand_call(ctx: MoveContext, scenario: CCLOp, *, count: int,
                root_src_dst: int = 0, func: ReduceFunc = ReduceFunc.SUM,
                tag: int = TAG_ANY, addr_0: int = 0, addr_1: int = 0,
                addr_2: int = 0,
                compression: Compression = Compression.NONE,
                stream: StreamFlags = StreamFlags.NO_STREAM,
                algorithm: CollectiveAlgorithm = CollectiveAlgorithm.AUTO,
                counts=None) -> list[Move]:
    """Dispatch a call descriptor to its expansion (see
    :func:`_expand_call_moves`), then apply the block-scaled wire
    post-pass: with ``Compression.BLOCK_SCALED`` every eth-compressed
    move is tagged ``Move.block_scaled`` in ONE place — per-site tagging
    across ~20 expansion functions would be one audit away from a relay
    that silently forwards unquantized bytes. Validation lives here too,
    so every tier (driver, python daemon, plan cache) rejects malformed
    block-scaled descriptors identically."""
    if compression & Compression.BLOCK_SCALED:
        from .quant import is_quantizable
        if not compression & Compression.ETH_COMPRESSED:
            raise ValueError(
                "BLOCK_SCALED is a wire-compression refinement: it "
                "requires ETH_COMPRESSED (the flag quantizes frames, "
                "not operand storage)")
        if compression & (Compression.OP0_COMPRESSED
                          | Compression.OP1_COMPRESSED
                          | Compression.RES_COMPRESSED):
            raise ValueError(
                "BLOCK_SCALED requires uncompressed operand storage: "
                "the combine lane dequantizes into (and requantizes "
                "from) the f32 accumulator, so compressed-stored "
                "operands cannot ride the block-scaled wire")
        if stream != StreamFlags.NO_STREAM:
            raise ValueError(
                "BLOCK_SCALED cannot combine with stream-port operands "
                "(stream lanes carry raw elements, not scale-block "
                "payloads)")
        if ctx.arithcfg.uncompressed_dtype.name != "float32" \
                or not is_quantizable(ctx.arithcfg.compressed_dtype):
            raise ValueError(
                f"BLOCK_SCALED supports float32 operands over an "
                f"int8/fp8 wire dtype; got "
                f"{ctx.arithcfg.uncompressed_dtype.name} over "
                f"{ctx.arithcfg.compressed_dtype.name}")
        if ctx.arithcfg.quant_block <= 0:
            raise ValueError(
                "BLOCK_SCALED descriptor reached expansion with an "
                "arith config carrying no quant_block — the driver/"
                "daemon must derive a block-scaled ArithConfig "
                "(segmentation depends on the scale-header reservation)")
    # NOTE deliberately NO engine-level rejection of plain float->int
    # narrowing: the move engine's astype semantics for hand-built
    # (f32, int8) configs long predate the quantized lane (the
    # property corpora pin them as the 1-byte compressed-dtype case).
    # The DRIVER rejects the user-facing path instead (_prepare): its
    # registry's (float32, int8) pair exists only for block_scale=.
    moves = _expand_call_moves(
        ctx, scenario, count=count, root_src_dst=root_src_dst, func=func,
        tag=tag, addr_0=addr_0, addr_1=addr_1, addr_2=addr_2,
        compression=compression, stream=stream, algorithm=algorithm,
        counts=counts)
    if compression & Compression.BLOCK_SCALED:
        for mv in moves:
            if mv.eth_compressed:
                mv.block_scaled = True
    return moves


def _expand_call_moves(ctx: MoveContext, scenario: CCLOp, *, count: int,
                       root_src_dst: int = 0,
                       func: ReduceFunc = ReduceFunc.SUM,
                       tag: int = TAG_ANY, addr_0: int = 0, addr_1: int = 0,
                       addr_2: int = 0,
                       compression: Compression = Compression.NONE,
                       stream: StreamFlags = StreamFlags.NO_STREAM,
                       algorithm: CollectiveAlgorithm = (
                           CollectiveAlgorithm.AUTO),
                       counts=None) -> list[Move]:
    """Dispatch a call descriptor to its expansion.

    Parity: the firmware's run_accl() switch (ccl_offload_control.c:1155-1296)
    plus the XRT driver's per-collective algorithm variants
    (xlnx-consts.hpp:43-66) expressed via ``algorithm``.
    addr_0 = op0/src buffer, addr_1 = op1 buffer, addr_2 = result buffer.
    """
    A = CollectiveAlgorithm
    alg = A(algorithm)
    # one validation table for every tier (constants.VALID_ALGORITHMS):
    # ops without an algorithm axis reject any explicit selector
    check_algorithm(scenario.name, alg)
    if alg == A.HIERARCHICAL:
        # driver-level program (accl_tpu/hier): a descriptor carrying it
        # should have been intercepted before issue — there is no
        # single-communicator move expansion to produce here
        raise ValueError(
            "HIERARCHICAL is a driver-level multi-communicator phase "
            "program (accl_tpu/hier); issue the collective through an "
            "ACCL driver with a configured hierarchy "
            "(ACCL.configure_hierarchy) instead of expanding it as a "
            "flat move program")

    def pick(op_algs: dict):
        """Resolve AUTO through the attached tuner (size/topology-aware),
        falling back to the shared DEFAULT_ALGORITHMS table. A driver
        with a tuner normally resolves AUTO before the descriptor is
        issued (so the choice also crosses the wire to daemon tiers);
        this engine-level path covers descriptors that arrive still
        carrying AUTO."""
        if alg != A.AUTO:
            return op_algs[alg]
        chosen = A.AUTO
        if ctx.tuner is not None:
            nbytes = count * ctx.arithcfg.uncompressed_elem_bytes
            chosen = A(ctx.tuner.select(scenario.name, ctx.world_size,
                                        nbytes))
        if chosen == A.AUTO or chosen not in op_algs:
            chosen = DEFAULT_ALGORITHMS[scenario.name]
        return op_algs[chosen]

    if scenario == CCLOp.nop:
        return []
    if scenario == CCLOp.copy:
        return expand_copy(ctx, count, addr_0, addr_2, compression, stream)
    if scenario == CCLOp.combine:
        return expand_combine(ctx, count, func, addr_0, addr_1, addr_2,
                              compression, stream)
    if scenario == CCLOp.send:
        # RES_STREAM on a send targets the peer's stream port instead of its
        # rx pool (remote-stream send, dma_mover.cpp:303).
        return expand_send(ctx, count, addr_0, root_src_dst, tag, compression,
                           stream,
                           to_remote_stream=bool(stream & StreamFlags.RES_STREAM))
    if scenario == CCLOp.recv:
        return expand_recv(ctx, count, root_src_dst, addr_2, tag, compression,
                           stream)
    if scenario == CCLOp.bcast:
        fn = pick({A.ROUND_ROBIN: expand_broadcast,
                   A.TREE: expand_broadcast_tree})
        return fn(ctx, count, root_src_dst, addr_0, compression)
    if scenario == CCLOp.scatter:
        fn = pick({A.ROUND_ROBIN: expand_scatter})
        return fn(ctx, count, root_src_dst, addr_0, addr_2, compression)
    if scenario == CCLOp.gather:
        fn = pick({A.RING: expand_gather_ring,
                   A.ROUND_ROBIN: expand_gather_direct,
                   A.TREE: expand_gather_tree})
        return fn(ctx, count, root_src_dst, addr_0, addr_2, compression)
    if scenario == CCLOp.reduce:
        fn = pick({A.RING: expand_reduce_ring,
                   A.ROUND_ROBIN: expand_reduce_direct,
                   A.TREE: expand_reduce_tree})
        return fn(ctx, count, root_src_dst, func, addr_0, addr_2, compression)
    if scenario == CCLOp.allgather:
        fn = pick({A.RING: expand_allgather_ring,
                   A.ROUND_ROBIN: expand_allgather_direct,
                   A.RECURSIVE_DOUBLING: expand_allgather_recursive_doubling})
        return fn(ctx, count, addr_0, addr_2, compression)
    if scenario == CCLOp.allreduce:
        fn = pick({A.RING: expand_allreduce_ring,
                   A.FUSED_RING: expand_allreduce_ring,
                   A.NON_FUSED: expand_allreduce_nonfused,
                   A.RECURSIVE_DOUBLING: expand_allreduce_rd})
        return fn(ctx, count, func, addr_0, addr_2, compression)
    if scenario == CCLOp.reduce_scatter:
        def _rs_rd(ctx, count, func, a0, a2, compression):
            return expand_reduce_scatter_recursive_halving(
                ctx, count, func, a0, a2, addr_1, compression)
        table = {A.RING: expand_reduce_scatter_ring}
        if addr_1 or alg == A.RECURSIVE_DOUBLING:
            # the halving needs the driver-plumbed scratch (addr_1). An
            # engine-level AUTO resolution on a raw descriptor without
            # one must fall back to RING (table omission -> pick's
            # DEFAULT path), while an EXPLICIT selector without scratch
            # reaches the expansion and fails loudly there.
            table[A.RECURSIVE_DOUBLING] = _rs_rd
        fn = pick(table)
        return fn(ctx, count, func, addr_0, addr_2, compression)
    if scenario == CCLOp.alltoall:
        return expand_alltoall(ctx, count, addr_0, addr_2, compression)
    if scenario == CCLOp.alltoallv:
        if counts is None:
            raise ValueError(
                "alltoallv requires a (send_counts, recv_counts) pair "
                "(CallDescriptor.counts / expand_call(counts=...))")
        send_counts, recv_counts = counts
        return expand_alltoallv(ctx, send_counts, recv_counts,
                                addr_0, addr_2, compression)
    raise NotImplementedError(f"scenario {scenario!r}")
