"""Control plane: expand collective calls into ``Move`` micro-operations.

This is the TPU-framework equivalent of the reference's MicroBlaze firmware
(kernels/cclo/fw/sw_apps/ccl_offload_control/src/ccl_offload_control.c):
every primitive/collective is expressed as a short program of generic *move*
micro-ops, each of which reads up to two operands (from memory, from the
receive-matching engine, or from a stream), optionally combines them
elementwise, and writes the result locally and/or sends it to a peer.

Design differences from the reference (deliberate, TPU-idiomatic):
  * The firmware resolves INCREMENT/REPEAT/STRIDE address modes *inside the
    dataplane* with per-channel previous-address registers
    (dma_mover.cpp:497-669). Here the engine resolves concrete byte
    addresses at expansion time and records the mode label for parity
    inspection — software expansion makes stateful address registers
    pointless.
  * Counts are elements of the call's uncompressed dtype; addresses are byte
    offsets into the rank's device memory.

Collective expansions mirror the reference algorithms one-for-one so a
reviewer can diff them against ccl_offload_control.c:502-1098:
ring gather/allgather/reduce/reduce_scatter, 2-phase ring allreduce
(fused reduce-scatter + allgather), segmented broadcast, strided scatter.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Iterator

from .arith import ArithConfig
from .constants import (CCLOp, CollectiveAlgorithm, Compression,
                        DEFAULT_ALGORITHMS, ReduceFunc, StreamFlags,
                        TAG_ANY, check_algorithm)


def res_as_op0(compression: Compression) -> Compression:
    """Remap the RES compressed-ness onto OP0: used when a follow-on stage
    reads the previous stage's result buffer as its operand (e.g. the
    bcast after a non-fused reduce, or the root folding into dst)."""
    out = compression & ~Compression.OP0_COMPRESSED
    if compression & Compression.RES_COMPRESSED:
        out |= Compression.OP0_COMPRESSED
    return out


class MoveMode(enum.Enum):
    """Operand sourcing/sinking modes.

    Parity: MOVE_NONE/STREAM/IMMEDIATE/ON_RECV/INCREMENT/REPEAT/STRIDE
    (ccl_offload_control.h:153-161). INCREMENT/REPEAT/STRIDE collapse to
    IMMEDIATE at expansion time; the ``mode_label`` field on Move keeps the
    original mode name for diffing against the firmware.
    """

    NONE = "none"
    IMMEDIATE = "immediate"
    ON_RECV = "on_recv"
    STREAM = "stream"


@dataclasses.dataclass
class Operand:
    mode: MoveMode = MoveMode.NONE
    addr: int | None = None          # byte address (IMMEDIATE)
    src_rank: int | None = None      # peer to match (ON_RECV)
    tag: int = TAG_ANY               # envelope tag (ON_RECV)
    compressed: bool = False         # operand stored in compressed dtype

    @classmethod
    def none(cls):
        return cls(MoveMode.NONE)

    @classmethod
    def imm(cls, addr: int, compressed: bool = False):
        return cls(MoveMode.IMMEDIATE, addr=addr, compressed=compressed)

    @classmethod
    def on_recv(cls, src_rank: int, tag: int = TAG_ANY):
        return cls(MoveMode.ON_RECV, src_rank=src_rank, tag=tag)

    @classmethod
    def stream(cls):
        return cls(MoveMode.STREAM)


@dataclasses.dataclass
class Move:
    """One micro-op: res = func(op0, op1), written locally and/or sent.

    Parity: ``move_instruction`` (dma_mover.h:28-74) — op0/op1/res operand
    specs, elementwise function, remote destination {rank, tag}, compression
    flags, count. ``blocking`` marks moves whose result must be fully
    retired before the next move may start (the reference forces this where
    a relay would race a concurrent write, ccl_offload_control.c:788-791).

    ``blocking=False`` invariant (what the pipelined executor relies on —
    audit every site that clears the flag against it): the move is a pure
    pool-destined send (no local write, no stream port) AND no later move
    of the same program writes the memory it reads. Such a move may retire
    asynchronously, overlapping subsequent moves; the executor keeps wire
    sequence numbers in program order regardless. A send whose source is
    rewritten later (gather's relay scratch, c:632-724) must stay blocking.

    ``lane`` invariant (what the segment-streamed executor relies on): a
    move tagged with a segment lane may execute concurrently with moves of
    OTHER lanes; within one lane, program order is preserved. The
    expansion tagging lane ``s`` therefore asserts that every byte the
    move reads or writes is disjoint from the bytes touched by every
    *concurrent* move of a different lane — segment ``s`` of step ``k+1``
    depends only on segment ``s`` of step ``k``, never on a sibling
    segment (the reference's dual-DataMover segment interleave,
    dma_mover.cpp:716-898). Moves whose hazards cannot be expressed that
    way (gather's reused relay scratch, stream-port moves) carry
    ``lane=None`` and serialize as barriers. Lane-chaining follows program
    order, so the implied dependency graph is acyclic by construction
    (``scripts/check_blocking.py`` lints both invariants).
    """

    count: int
    op0: Operand = dataclasses.field(default_factory=Operand.none)
    op1: Operand = dataclasses.field(default_factory=Operand.none)
    res: Operand = dataclasses.field(default_factory=Operand.none)
    func: ReduceFunc | None = None
    res_remote: bool = False
    res_local: bool = False
    dst_rank: int | None = None      # remote destination rank
    tag: int = 0                     # tag for the outgoing message
    eth_compressed: bool = False     # compress on the wire
    remote_stream: bool = False      # deliver to peer's stream, not rx pool
    blocking: bool = True
    lane: int | None = None          # segment lane (see class docstring)
    mode_label: str = ""             # firmware address-mode annotation


def _seg_elems(arithcfg: ArithConfig, max_segment_size: int,
               eth_compressed: bool) -> int:
    """Elements per wire segment.

    Parity: the firmware computes segment element count from
    max_segment_size / elem bytes, using the *wire* element size when the
    message is compressed (broadcast, ccl_offload_control.c:530-535).
    """
    elem = (arithcfg.compressed_elem_bytes if eth_compressed
            else arithcfg.uncompressed_elem_bytes)
    return max(1, max_segment_size // max(1, elem))


def _segments(count: int, seg: int) -> Iterator[tuple[int, int]]:
    """Yield (offset_elems, nelems) chunks of a count."""
    off = 0
    while off < count:
        n = min(seg, count - off)
        yield off, n
        off += n


@dataclasses.dataclass
class MoveContext:
    """Everything an expansion needs besides the call itself."""

    world_size: int
    local_rank: int
    arithcfg: ArithConfig
    max_segment_size: int
    # Optional attached Tuner (accl_tpu/tuner): consulted by expand_call
    # when a descriptor still carries CollectiveAlgorithm.AUTO at the
    # engine (duck-typed — anything with .select(op, world, nbytes)).
    tuner: Any = None

    def ebytes(self, compressed: bool = False) -> int:
        return (self.arithcfg.compressed_elem_bytes if compressed
                else self.arithcfg.uncompressed_elem_bytes)


# ---------------------------------------------------------------------------
# Primitives (parity: ccl_offload_control.c:301-500)
# ---------------------------------------------------------------------------

def expand_copy(ctx: MoveContext, count: int, src: int, dst: int,
                compression: Compression = Compression.NONE,
                stream: StreamFlags = StreamFlags.NO_STREAM) -> list[Move]:
    """copy (c:301-315): one local move op0->res."""
    op0 = (Operand.stream() if stream & StreamFlags.OP0_STREAM
           else Operand.imm(src, bool(compression & Compression.OP0_COMPRESSED)))
    res = (Operand.stream() if stream & StreamFlags.RES_STREAM
           else Operand.imm(dst, bool(compression & Compression.RES_COMPRESSED)))
    return [Move(count=count, op0=op0, res=res, res_local=True,
                 mode_label="IMMEDIATE/NONE/IMMEDIATE")]


def expand_combine(ctx: MoveContext, count: int, func: ReduceFunc,
                   op0: int, op1: int, dst: int,
                   compression: Compression = Compression.NONE,
                   stream: StreamFlags = StreamFlags.NO_STREAM) -> list[Move]:
    """combine (c:319-335): res = func(op0, op1) locally. OP0/RES stream
    flags source the first operand from / sink the result to the
    external-kernel ports, like copy (the combine-from-stream shape of
    the reference's plugin datapath)."""
    s_op0 = bool(stream & StreamFlags.OP0_STREAM)
    s_res = bool(stream & StreamFlags.RES_STREAM)
    return [Move(
        count=count,
        op0=(Operand.stream() if s_op0
             else Operand.imm(op0,
                              bool(compression & Compression.OP0_COMPRESSED))),
        op1=Operand.imm(op1, bool(compression & Compression.OP1_COMPRESSED)),
        res=(Operand.stream() if s_res
             else Operand.imm(dst,
                              bool(compression & Compression.RES_COMPRESSED))),
        func=func, res_local=True,
        mode_label=(f"{'STREAM' if s_op0 else 'IMMEDIATE'}/IMMEDIATE/"
                    f"{'STREAM' if s_res else 'IMMEDIATE'}"))]


def expand_send(ctx: MoveContext, count: int, src: int, dst_rank: int,
                tag: int = 0,
                compression: Compression = Compression.NONE,
                stream: StreamFlags = StreamFlags.NO_STREAM,
                to_remote_stream: bool = False,
                blocking: bool = True, laned: bool = False) -> list[Move]:
    """send (c:339-361): segmented op0 -> remote res.

    Wire compression applies when ETH_COMPRESSED is set; segmentation at
    max_segment_size like the eth_cmd split (dma_mover.cpp:280-318).
    ``blocking=False`` is passed by callers whose source region is never
    written later in the program (see the Move.blocking invariant) so the
    pipelined executor can overlap the send with subsequent moves.
    ``laned=True`` additionally tags each segment with its lane — callers
    assert the Move.lane invariant: segment ``s`` reads only bytes written
    by earlier moves of lane ``s`` (the relay-from-slot shape).
    """
    eth_c = bool(compression & Compression.ETH_COMPRESSED)
    moves = []
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size, eth_c)
    ebytes = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    for si, (off, n) in enumerate(_segments(count, seg)):
        op0 = (Operand.stream() if stream & StreamFlags.OP0_STREAM
               else Operand.imm(src + off * ebytes,
                                bool(compression & Compression.OP0_COMPRESSED)))
        moves.append(Move(count=n, op0=op0, res_remote=True,
                          dst_rank=dst_rank, tag=tag, eth_compressed=eth_c,
                          remote_stream=to_remote_stream, blocking=blocking,
                          lane=si if laned else None,
                          mode_label="IMMEDIATE/NONE/REMOTE"))
    return moves


def expand_recv(ctx: MoveContext, count: int, src_rank: int, dst: int,
                tag: int = 0,
                compression: Compression = Compression.NONE,
                stream: StreamFlags = StreamFlags.NO_STREAM) -> list[Move]:
    """recv (c:365-380): segmented ON_RECV -> local res.

    Each segment carries its lane tag: segment ``s`` writes only its own
    slice of ``dst``, so recv-matching of segment ``s+1`` may overlap the
    consumption of segment ``s`` (Move.lane invariant; the one consumer
    that re-reads the written slice — a relay — rides the SAME lane).
    """
    eth_c = bool(compression & Compression.ETH_COMPRESSED)
    moves = []
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size, eth_c)
    ebytes = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    for si, (off, n) in enumerate(_segments(count, seg)):
        res = (Operand.stream() if stream & StreamFlags.RES_STREAM
               else Operand.imm(dst + off * ebytes,
                                bool(compression & Compression.RES_COMPRESSED)))
        moves.append(Move(count=n, op1=Operand.on_recv(src_rank, tag),
                          res=res, res_local=True, eth_compressed=eth_c,
                          lane=si,
                          mode_label="NONE/ON_RECV/IMMEDIATE"))
    return moves


def expand_fused_recv_reduce(ctx: MoveContext, count: int, func: ReduceFunc,
                             src_rank: int, op0: int, dst: int, tag: int = 0,
                             compression: Compression = Compression.NONE,
                             ) -> list[Move]:
    """fused_recv_reduce (c:441-467): res = func(op0, incoming).

    Lane-tagged per segment: segment ``s`` reads op0 slice ``s`` and
    writes res slice ``s`` only, so lanes are pairwise disjoint and the
    combine of segment ``s`` overlaps the recv-match of ``s+1``
    (Move.lane invariant). Chained folds that read the previous fold's
    res as op0 (reduce_direct) are ordered lane-locally for free.
    """
    eth_c = bool(compression & Compression.ETH_COMPRESSED)
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size, eth_c)
    e0 = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    er = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    moves = []
    for si, (off, n) in enumerate(_segments(count, seg)):
        moves.append(Move(
            count=n,
            op0=Operand.imm(op0 + off * e0,
                            bool(compression & Compression.OP0_COMPRESSED)),
            op1=Operand.on_recv(src_rank, tag),
            res=Operand.imm(dst + off * er,
                            bool(compression & Compression.RES_COMPRESSED)),
            func=func, res_local=True, eth_compressed=eth_c, lane=si,
            mode_label="IMMEDIATE/ON_RECV/IMMEDIATE"))
    return moves


def expand_fused_recv_reduce_send(ctx: MoveContext, count: int,
                                  func: ReduceFunc, src_rank: int,
                                  dst_rank: int, op0: int, tag: int = 0,
                                  dst: int | None = None,
                                  compression: Compression = Compression.NONE,
                                  ) -> list[Move]:
    """fused_recv_reduce_send (c:473-500): func(op0, incoming) -> peer
    (and optionally also to local dst — the RES_REMOTE|RES_LOCAL form used
    by allreduce phase 1, c:993-1023). Lane-tagged per segment like
    ``expand_fused_recv_reduce`` — the recv→combine→relay of segment ``s``
    forms one lane, so the relay of ``s-1`` streams out while ``s``
    combines and ``s+1`` recv-matches."""
    eth_c = bool(compression & Compression.ETH_COMPRESSED)
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size, eth_c)
    e0 = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    er = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    moves = []
    for si, (off, n) in enumerate(_segments(count, seg)):
        res = (Operand.imm(dst + off * er,
                           bool(compression & Compression.RES_COMPRESSED))
               if dst is not None else Operand.none())
        moves.append(Move(
            count=n,
            op0=Operand.imm(op0 + off * e0,
                            bool(compression & Compression.OP0_COMPRESSED)),
            op1=Operand.on_recv(src_rank, tag),
            res=res, func=func,
            res_remote=True, res_local=dst is not None,
            dst_rank=dst_rank, tag=tag, eth_compressed=eth_c, lane=si,
            mode_label="IMMEDIATE/ON_RECV/REMOTE(+LOCAL)"))
    return moves


# ---------------------------------------------------------------------------
# Collectives (parity: ccl_offload_control.c:502-1098)
# ---------------------------------------------------------------------------

def expand_broadcast(ctx: MoveContext, count: int, root: int, buf: int,
                     compression: Compression = Compression.NONE) -> list[Move]:
    """broadcast (c:507-571): root sends each segment to every peer
    (firmware: IMMEDIATE then MOVE_REPEAT to reuse the segment); non-root
    receives segments in order."""
    moves: list[Move] = []
    eth_c = bool(compression & Compression.ETH_COMPRESSED)
    seg = _seg_elems(ctx.arithcfg, ctx.max_segment_size, eth_c)
    ebytes = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    if ctx.local_rank == root:
        # non-blocking: buf is never written by this program's later
        # moves; laned per segment so a caller that DID write buf earlier
        # (the non-fused allreduce reduces into it lane-by-lane) hands
        # each segment's fan-out a lane-local dependency on that write
        for si, (off, n) in enumerate(_segments(count, seg)):
            first = True
            for r in range(ctx.world_size):
                if r == root:
                    continue
                moves.append(Move(
                    count=n,
                    op0=Operand.imm(buf + off * ebytes,
                                    bool(compression & Compression.OP0_COMPRESSED)),
                    res_remote=True, dst_rank=r, tag=TAG_ANY,
                    eth_compressed=eth_c, blocking=False, lane=si,
                    mode_label="IMMEDIATE" if first else "REPEAT"))
                first = False
    else:
        moves += expand_recv(ctx, count, root, buf, tag=TAG_ANY,
                             compression=compression)
    return moves


def expand_broadcast_tree(ctx: MoveContext, count: int, root: int, buf: int,
                          compression: Compression = Compression.NONE
                          ) -> list[Move]:
    """broadcast, binomial tree: log2(W) rounds instead of the firmware's
    W-1 sequential sends (a TPU-native latency-optimal variant; the
    reference reserves the algorithm axis in xlnx-consts.hpp:43-66, and its
    2D-mesh analog is parallel/tree.py). Each rank receives once from its
    tree parent, then forwards to progressively nearer sub-roots."""
    W, me = ctx.world_size, ctx.local_rank
    if W == 1:
        return []
    vrank = (me - root) % W
    moves: list[Move] = []
    mask = 1
    while mask < W:
        if vrank & mask:
            parent = ((vrank ^ mask) + root) % W
            moves += expand_recv(ctx, count, parent, buf, tag=TAG_ANY,
                                 compression=compression)
            break
        mask <<= 1
    mask >>= 1
    while mask:
        if vrank + mask < W:
            child = ((vrank + mask) + root) % W
            # non-blocking: buf is never written after the (earlier) recv,
            # so forwards to all children may overlap each other; laned:
            # the forward of segment s reads only the slice the recv of
            # lane s wrote, so it chains behind that recv and streams out
            # while later segments are still arriving
            moves += expand_send(ctx, count, buf, child, tag=TAG_ANY,
                                 compression=compression, blocking=False,
                                 laned=True)
        mask >>= 1
    return moves


def expand_scatter(ctx: MoveContext, count: int, root: int, src: int,
                   dst: int,
                   compression: Compression = Compression.NONE) -> list[Move]:
    """scatter (c:575-627): root strided round-robin sends + local copy of
    its own chunk; non-root receives ``count`` elements. ``count`` is the
    per-rank chunk size (reference semantics)."""
    moves: list[Move] = []
    ebytes = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    if ctx.local_rank == root:
        for r in range(ctx.world_size):
            chunk = src + r * count * ebytes
            if r == root:
                moves += expand_copy(ctx, count, chunk, dst, compression)
                moves[-1].mode_label = "INCREMENT(local-copy)"
            else:
                # non-blocking: src chunks are read-only for the whole call
                sends = expand_send(ctx, count, chunk, r, tag=TAG_ANY,
                                    compression=compression, blocking=False)
                for m in sends:
                    m.mode_label = "INCREMENT(rr-send)"
                moves += sends
    else:
        moves += expand_recv(ctx, count, root, dst, tag=TAG_ANY,
                             compression=compression)
    return moves


def expand_gather_ring(ctx: MoveContext, count: int, root: int, src: int,
                       dst: int,
                       compression: Compression = Compression.NONE) -> list[Move]:
    """gather, ring algorithm (c:632-724): non-root sends its chunk to the
    previous ring neighbor toward root, then relays ``dist-1`` incoming
    chunks; root receives ``world_size-1`` chunks from its next neighbor
    into reverse-ring strided slots plus a local copy of its own."""
    W, me = ctx.world_size, ctx.local_rank
    ebytes = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    moves: list[Move] = []
    # distance from root along the ring (how many hops my data travels)
    dist = (me - root) % W
    prev_in_ring = (me + 1) % W   # data flows decreasing-rank toward root
    next_toward_root = (me - 1) % W
    if me == root:
        moves += expand_copy(ctx, count, src, dst + me * count * ebytes,
                             compression)
        for i in range(W - 1):
            # chunk arriving i-th belongs to rank (root+1+i) ... relayed in
            # arrival order from the next ring neighbor
            owner = (root + 1 + i) % W
            moves += expand_recv(ctx, count, prev_in_ring,
                                 dst + owner * count * ebytes, tag=TAG_ANY,
                                 compression=compression)
    else:
        # non-blocking: src is never written during a gather
        moves += expand_send(ctx, count, src, next_toward_root, tag=TAG_ANY,
                             compression=compression, blocking=False)
        # relay the chunks of the (W-1-dist) ranks farther from root
        relay_buf = dst  # non-root dst is scratch (reference reuses rx path)
        for _ in range(W - 1 - dist):
            moves += expand_recv(ctx, count, prev_in_ring, relay_buf,
                                 tag=TAG_ANY, compression=compression)
            # the relay reads the RES-typed scratch the recv just wrote —
            # and the NEXT recv overwrites that same scratch, so this send
            # must stay blocking (WAR hazard on relay_buf)
            moves += expand_send(ctx, count, relay_buf, next_toward_root,
                                 tag=TAG_ANY,
                                 compression=res_as_op0(compression))
    return moves


def expand_gather_direct(ctx: MoveContext, count: int, root: int, src: int,
                         dst: int,
                         compression: Compression = Compression.NONE
                         ) -> list[Move]:
    """gather, round-robin/direct (reference ``gather_rr``,
    xlnx-consts.hpp): every non-root sends its chunk straight to root;
    root receives W-1 strided chunks (pool matching absorbs arrival
    order) plus a local copy of its own."""
    W, me = ctx.world_size, ctx.local_rank
    ebytes = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    moves: list[Move] = []
    if me == root:
        moves += expand_copy(ctx, count, src, dst + me * count * ebytes,
                             compression)
        for r in range(W):
            if r == root:
                continue
            moves += expand_recv(ctx, count, r, dst + r * count * ebytes,
                                 tag=TAG_ANY, compression=compression)
    else:
        # non-blocking: the send is the non-root's whole program
        moves += expand_send(ctx, count, src, root, tag=TAG_ANY,
                             compression=compression, blocking=False)
    return moves


def expand_allgather_ring(ctx: MoveContext, count: int, src: int, dst: int,
                          compression: Compression = Compression.NONE
                          ) -> list[Move]:
    """allgather, ring (c:727-828): copy own chunk into its slot, send it to
    the next neighbor, then W-1 × {blocking recv into the originating
    rank's slot, relay onward}. The recv must retire before the relay reads
    the slot — the reference's explicit RAW-race note (c:788-791)."""
    W, me = ctx.world_size, ctx.local_rank
    ebytes = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    nxt, prv = (me + 1) % W, (me - 1) % W
    moves: list[Move] = []
    moves += expand_copy(ctx, count, src, dst + me * count * ebytes,
                         compression)
    # non-blocking: src is never written during an allgather, so the
    # initial send overlaps the first recv's pool wait; laned so segment
    # lanes align with the per-segment recv→relay chains below
    moves += expand_send(ctx, count, src, nxt, tag=TAG_ANY,
                         compression=compression, blocking=False,
                         laned=True)
    for i in range(W - 1):
        owner = (me - 1 - i) % W
        slot = dst + owner * count * ebytes
        rx = expand_recv(ctx, count, prv, slot, tag=TAG_ANY,
                         compression=compression)
        for m in rx:
            m.blocking = True  # RAW hazard vs the relay below (c:788-791)
        moves += rx
        if i < W - 2:
            # the relay reads the slot the recv just wrote, which is stored
            # in the RES dtype — substitute the flag like the firmware's
            # ETH/OP0 substitution when relaying from dst (c:739-743).
            # Non-blocking: each round's slot is written exactly once, so
            # the relay overlaps the NEXT round's recv (different slot) —
            # the ring-step overlap the pipelined executor exploits.
            # Laned: relay of segment s reads exactly the slice lane s's
            # recv wrote, so the RAW hazard is a lane-local edge and
            # sibling segments stream independently.
            moves += expand_send(ctx, count, slot, nxt, tag=TAG_ANY,
                                 compression=res_as_op0(compression),
                                 blocking=False, laned=True)
    return moves


def expand_allgather_direct(ctx: MoveContext, count: int, src: int, dst: int,
                            compression: Compression = Compression.NONE
                            ) -> list[Move]:
    """allgather, direct fan-out (round-robin): every rank eagerly sends
    its chunk to all peers, then receives W-1 chunks into their slots.
    One hop of latency vs the ring's W-1, at W× the injection rate."""
    W, me = ctx.world_size, ctx.local_rank
    ebytes = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    moves: list[Move] = []
    moves += expand_copy(ctx, count, src, dst + me * count * ebytes,
                         compression)
    for step in range(1, W):  # rotated schedule avoids hot receivers
        to = (me + step) % W
        # non-blocking: src is read-only; the recvs below write dst slots
        moves += expand_send(ctx, count, src, to, tag=TAG_ANY,
                             compression=compression, blocking=False)
    for step in range(1, W):
        frm = (me - step) % W
        moves += expand_recv(ctx, count, frm, dst + frm * count * ebytes,
                             tag=TAG_ANY, compression=compression)
    return moves


def expand_reduce_direct(ctx: MoveContext, count: int, root: int,
                         func: ReduceFunc, src: int, dst: int,
                         compression: Compression = Compression.NONE
                         ) -> list[Move]:
    """reduce, round-robin/direct (reference ``reduce_rr``): non-roots send
    straight to root; root folds arrivals into dst one sender at a time
    (first fold reads the root's own src as op0, later folds read dst)."""
    W, me = ctx.world_size, ctx.local_rank
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    moves: list[Move] = []
    if me != root:
        return expand_send(ctx, count, src, root, tag=TAG_ANY,
                           compression=compression)
    first = True
    for r in range(W):
        if r == root:
            continue
        # later folds read dst as op0, whose compressed-ness is the RES flag
        op0 = src if first else dst
        comp = compression if first else res_as_op0(compression)
        moves += expand_fused_recv_reduce(ctx, count, func, r, op0, dst,
                                          tag=TAG_ANY, compression=comp)
        first = False
    return moves


def expand_reduce_ring(ctx: MoveContext, count: int, root: int, func: ReduceFunc,
                       src: int, dst: int,
                       compression: Compression = Compression.NONE
                       ) -> list[Move]:
    """reduce, ring daisy chain (c:832-856): the rank after root plain-sends;
    middle ranks fused-recv-reduce-send; root fused-recv-reduces into dst."""
    W, me = ctx.world_size, ctx.local_rank
    nxt, prv = (me - 1) % W, (me + 1) % W  # data flows toward root
    moves: list[Move] = []
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    if (me - root) % W == W - 1:
        # farthest rank starts the chain; non-blocking: src is read-only
        # and this send is the rank's whole program (laned so downstream
        # per-segment fused chains see aligned lanes)
        moves += expand_send(ctx, count, src, nxt, tag=TAG_ANY,
                             compression=compression, blocking=False,
                             laned=True)
    elif me == root:
        moves += expand_fused_recv_reduce(ctx, count, func, prv, src, dst,
                                          tag=TAG_ANY, compression=compression)
    else:
        moves += expand_fused_recv_reduce_send(ctx, count, func, prv, nxt,
                                               src, tag=TAG_ANY,
                                               compression=compression)
    return moves


def expand_reduce_scatter_ring(ctx: MoveContext, count: int, func: ReduceFunc,
                               src: int, dst: int,
                               compression: Compression = Compression.NONE
                               ) -> list[Move]:
    """reduce_scatter, ring (c:860-939): send your (me+1)'th chunk, then for
    W-1 rounds fused recv+reduce+forward walking chunks backwards; the last
    round reduces into local dst (your own chunk). ``count`` is the
    per-rank chunk size."""
    W, me = ctx.world_size, ctx.local_rank
    ebytes = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    nxt, prv = (me - 1) % W, (me + 1) % W
    moves: list[Move] = []
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    first_chunk = (me + 1) % W
    # non-blocking: src chunks are read-only; the only local write of the
    # program is the final fused reduce into dst. Laned: the kickoff of
    # segment s feeds the downstream rank's lane-s fused chain.
    moves += expand_send(ctx, count, src + first_chunk * count * ebytes, nxt,
                         tag=TAG_ANY, compression=compression,
                         blocking=False, laned=True)
    for i in range(1, W):
        # flow is toward decreasing rank, so at round i the partial arriving
        # from prv=(me+1) is for chunk (me+1+i); the final round's chunk is
        # my own (me+W = me), saved locally — matching the reference's
        # "last iteration saves locally" (c:860-939).
        chunk = (me + 1 + i) % W
        op0 = src + chunk * count * ebytes
        if i < W - 1:
            moves += expand_fused_recv_reduce_send(
                ctx, count, func, prv, nxt, op0, tag=TAG_ANY,
                compression=compression)
        else:
            # final round: chunk == me; reduce into local dst
            moves += expand_fused_recv_reduce(
                ctx, count, func, prv, op0, dst, tag=TAG_ANY,
                compression=compression)
    return moves


def expand_allreduce_ring(ctx: MoveContext, count: int, func: ReduceFunc,
                          src: int, dst: int,
                          compression: Compression = Compression.NONE
                          ) -> list[Move]:
    """allreduce = fused ring reduce-scatter phase + ring allgather phase
    (c:942-1098). ``count`` is the *total* element count; chunking into W
    near-equal chunks with a bulk/tail split like the firmware
    (c:966-967)."""
    W, me = ctx.world_size, ctx.local_rank
    if W == 1:
        return expand_copy(ctx, count, src, dst, compression)
    # src chunks live in the OP0 dtype, dst chunks in the RES dtype — offsets
    # must be computed with each buffer's own element size (the firmware's
    # allreduce recomputes addresses per phase, c:966-979, 1031-1045)
    e_src = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    e_dst = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    bulk = count // W
    tail = count - bulk * (W - 1)  # last chunk absorbs the remainder

    def src_off(c: int) -> int:
        return src + c * bulk * e_src

    def dst_off(c: int) -> int:
        return dst + c * bulk * e_dst

    def chunk_len(c: int) -> int:
        return tail if c == W - 1 else bulk

    nxt, prv = (me - 1) % W, (me + 1) % W
    moves: list[Move] = []

    # --- phase 1: ring reduce-scatter over chunks (c:982-1023) ---
    # non-blocking: src chunks are read-only for the whole allreduce, so
    # the phase-1 kickoff send overlaps the first fused step's pool wait
    c0 = (me + 1) % W
    if chunk_len(c0):
        # laned: kickoff segment s is what the downstream lane-s fused
        # chain consumes first
        moves += expand_send(ctx, chunk_len(c0), src_off(c0), nxt,
                             tag=TAG_ANY, compression=compression,
                             blocking=False, laned=True)
    for i in range(1, W):
        c = (me + 1 + i) % W  # decreasing-rank flow: see reduce_scatter
        if not chunk_len(c):
            continue
        if i < W - 1:
            moves += expand_fused_recv_reduce_send(
                ctx, chunk_len(c), func, prv, nxt, src_off(c),
                tag=TAG_ANY, compression=compression)
        else:
            # c == me: own fully-reduced chunk lands in dst
            moves += expand_fused_recv_reduce(
                ctx, chunk_len(c), func, prv, src_off(c),
                dst_off(c), tag=TAG_ANY, compression=compression)

    # --- phase 2: ring allgather of reduced chunks from dst (c:1031-1095) ---
    # every phase-2 read sources the RES-typed dst buffer, so the OP0 flag is
    # substituted with the RES flag (the firmware reads dst with the RES
    # compression in its allgather phase, c:1031-1095)
    p2 = res_as_op0(compression)
    # non-blocking sends throughout phase 2: every dst slot is written
    # exactly once (own chunk by phase 1, each other chunk by its recv),
    # so a relay's source is never rewritten and the relay overlaps the
    # next round's recv — the per-step overlap the pipelined executor
    # turns into throughput (the serial engine pays send+recv in sequence)
    if chunk_len(me):
        # laned: the phase-2 kickoff of segment s reads the dst slice the
        # phase-1 final fused move of lane s wrote — same lane, so the
        # cross-phase RAW hazard is a lane-local edge and the kickoff of
        # segment s streams out while segment s+1 is still reducing
        moves += expand_send(ctx, chunk_len(me), dst_off(me), nxt,
                             tag=TAG_ANY, compression=p2, blocking=False,
                             laned=True)
    for i in range(1, W):
        c = (me + i) % W  # decreasing-rank flow: chunk me+i arrives at round i
        if not chunk_len(c):
            continue
        slot = dst_off(c)
        rx = expand_recv(ctx, chunk_len(c), prv, slot, tag=TAG_ANY,
                         compression=compression)
        for m in rx:
            m.blocking = True  # relay reads the slot next (c:1058-1061)
        moves += rx
        if i < W - 1:
            # laned: relay of segment s reads exactly what lane s's recv
            # wrote (slot written once per round), sibling lanes disjoint
            moves += expand_send(ctx, chunk_len(c), slot, nxt, tag=TAG_ANY,
                                 compression=p2, blocking=False, laned=True)
    return moves


def expand_allreduce_nonfused(ctx: MoveContext, count: int, func: ReduceFunc,
                              src: int, dst: int,
                              compression: Compression = Compression.NONE
                              ) -> list[Move]:
    """allreduce, non-fused (the reference's sw-orchestrated variant axis,
    xlnx-consts.hpp:43-66): ring reduce to rank 0, then broadcast of dst.
    2(W-1) serial hops vs the fused ring's bandwidth-optimal schedule —
    kept as a selectable algorithm for small messages and for diffing."""
    moves = expand_reduce_ring(ctx, count, 0, func, src, dst, compression)
    # the bcast reads/writes dst, whose compressed-ness is RES_COMPRESSED;
    # bcast addresses its buffer via the OP0 flag
    moves += expand_broadcast(ctx, count, 0, dst, res_as_op0(compression))
    return moves


def expand_alltoall(ctx: MoveContext, count: int, src: int, dst: int,
                    compression: Compression = Compression.NONE) -> list[Move]:
    """alltoall (capability extension; the reference reserves the op in its
    XRT enums): rank r sends chunk d to rank d and receives chunk s from
    every s. ``count`` is the per-pair chunk size."""
    W, me = ctx.world_size, ctx.local_rank
    # src chunks are OP0-typed, dst chunks RES-typed — separate element sizes
    e_src = ctx.ebytes(bool(compression & Compression.OP0_COMPRESSED))
    e_dst = ctx.ebytes(bool(compression & Compression.RES_COMPRESSED))
    moves: list[Move] = []
    moves += expand_copy(ctx, count, src + me * count * e_src,
                         dst + me * count * e_dst, compression)
    # round-robin schedule avoiding head-of-line blocking. A send may be
    # non-blocking (overlap its round's recv) only when no LATER recv
    # writes the chunk index it reads: step s sends chunk (me+s) and step
    # t recvs chunk (me-t), colliding when t == W-s — an IN-PLACE
    # alltoall (src aliasing dst) would hand the overlapped send a
    # rewritten source. The colliding recv is later than the send
    # exactly when W-s >= s, so the first half of the schedule stays
    # blocking and the second half overlaps.
    for step in range(1, W):
        to = (me + step) % W
        frm = (me - step) % W
        moves += expand_send(ctx, count, src + to * count * e_src, to,
                             tag=TAG_ANY, compression=compression,
                             blocking=(W - step) >= step)
        moves += expand_recv(ctx, count, frm, dst + frm * count * e_dst,
                             tag=TAG_ANY, compression=compression)
    return moves


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

def expand_call(ctx: MoveContext, scenario: CCLOp, *, count: int,
                root_src_dst: int = 0, func: ReduceFunc = ReduceFunc.SUM,
                tag: int = TAG_ANY, addr_0: int = 0, addr_1: int = 0,
                addr_2: int = 0,
                compression: Compression = Compression.NONE,
                stream: StreamFlags = StreamFlags.NO_STREAM,
                algorithm: CollectiveAlgorithm = CollectiveAlgorithm.AUTO
                ) -> list[Move]:
    """Dispatch a call descriptor to its expansion.

    Parity: the firmware's run_accl() switch (ccl_offload_control.c:1155-1296)
    plus the XRT driver's per-collective algorithm variants
    (xlnx-consts.hpp:43-66) expressed via ``algorithm``.
    addr_0 = op0/src buffer, addr_1 = op1 buffer, addr_2 = result buffer.
    """
    A = CollectiveAlgorithm
    alg = A(algorithm)
    # one validation table for every tier (constants.VALID_ALGORITHMS):
    # ops without an algorithm axis reject any explicit selector
    check_algorithm(scenario.name, alg)

    def pick(op_algs: dict):
        """Resolve AUTO through the attached tuner (size/topology-aware),
        falling back to the shared DEFAULT_ALGORITHMS table. A driver
        with a tuner normally resolves AUTO before the descriptor is
        issued (so the choice also crosses the wire to daemon tiers);
        this engine-level path covers descriptors that arrive still
        carrying AUTO."""
        if alg != A.AUTO:
            return op_algs[alg]
        chosen = A.AUTO
        if ctx.tuner is not None:
            nbytes = count * ctx.arithcfg.uncompressed_elem_bytes
            chosen = A(ctx.tuner.select(scenario.name, ctx.world_size,
                                        nbytes))
        if chosen == A.AUTO or chosen not in op_algs:
            chosen = DEFAULT_ALGORITHMS[scenario.name]
        return op_algs[chosen]

    if scenario == CCLOp.nop:
        return []
    if scenario == CCLOp.copy:
        return expand_copy(ctx, count, addr_0, addr_2, compression, stream)
    if scenario == CCLOp.combine:
        return expand_combine(ctx, count, func, addr_0, addr_1, addr_2,
                              compression, stream)
    if scenario == CCLOp.send:
        # RES_STREAM on a send targets the peer's stream port instead of its
        # rx pool (remote-stream send, dma_mover.cpp:303).
        return expand_send(ctx, count, addr_0, root_src_dst, tag, compression,
                           stream,
                           to_remote_stream=bool(stream & StreamFlags.RES_STREAM))
    if scenario == CCLOp.recv:
        return expand_recv(ctx, count, root_src_dst, addr_2, tag, compression,
                           stream)
    if scenario == CCLOp.bcast:
        fn = pick({A.ROUND_ROBIN: expand_broadcast,
                   A.TREE: expand_broadcast_tree})
        return fn(ctx, count, root_src_dst, addr_0, compression)
    if scenario == CCLOp.scatter:
        fn = pick({A.ROUND_ROBIN: expand_scatter})
        return fn(ctx, count, root_src_dst, addr_0, addr_2, compression)
    if scenario == CCLOp.gather:
        fn = pick({A.RING: expand_gather_ring,
                   A.ROUND_ROBIN: expand_gather_direct})
        return fn(ctx, count, root_src_dst, addr_0, addr_2, compression)
    if scenario == CCLOp.reduce:
        fn = pick({A.RING: expand_reduce_ring,
                   A.ROUND_ROBIN: expand_reduce_direct})
        return fn(ctx, count, root_src_dst, func, addr_0, addr_2, compression)
    if scenario == CCLOp.allgather:
        fn = pick({A.RING: expand_allgather_ring,
                   A.ROUND_ROBIN: expand_allgather_direct})
        return fn(ctx, count, addr_0, addr_2, compression)
    if scenario == CCLOp.allreduce:
        fn = pick({A.RING: expand_allreduce_ring,
                   A.FUSED_RING: expand_allreduce_ring,
                   A.NON_FUSED: expand_allreduce_nonfused})
        return fn(ctx, count, func, addr_0, addr_2, compression)
    if scenario == CCLOp.reduce_scatter:
        fn = pick({A.RING: expand_reduce_scatter_ring})
        return fn(ctx, count, func, addr_0, addr_2, compression)
    if scenario == CCLOp.alltoall:
        return expand_alltoall(ctx, count, addr_0, addr_2, compression)
    raise NotImplementedError(f"scenario {scenario!r}")
