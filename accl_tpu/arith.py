"""Arithmetic / datatype configuration registry.

An :class:`ArithConfig` describes, for a pair of (uncompressed, compressed)
datatypes, how operands are elementwise-combined and how they are
(de)compressed for the wire. The driver resolves each call's dtype pair to a
config and hands the device backend everything it needs — exactly the role of
the reference's exchange-memory arithmetic config blobs.

Parity: reference ``ACCLArithConfig`` (driver/pynq/accl.py:207-255) stores
{uncompressed/compressed elem bytes, ratio, func count, arith TDEST, and
compressor/decompressor TDESTs}; configs are written to exchange memory at
init (accl.py:436-442) and addressed per-call (accl.py:528-592). On TPU the
"TDEST routing to a reduce_sum_<dtype> kernel" becomes dtype dispatch into
XLA/Pallas reductions, and the fp32<->fp16 compression lanes
(kernels/plugins/{fp_hp,hp_fp}_stream_conv) become dtype casts fused into the
collective program (see ops/compression kernels).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .constants import Compression, ReduceFunc


def combine_reducer(func: ReduceFunc, dtype):
    """The combine kernel for (func, dtype): compiled contiguous-span
    loops from ``native/combine_kernels.c`` when the extension is
    available, else the numpy ufunc — bit-identical either way (the
    differential corpora hold both). This is the arithmetic-dispatch
    half of the reference's TDEST routing into the per-dtype
    ``reduce_sum`` plugins: the executor resolves once per move and the
    per-segment call is one compiled loop, not a ufunc dispatch."""
    from . import native_combine
    return native_combine.reducer(func, dtype)


@dataclasses.dataclass(frozen=True)
class ArithConfig:
    """Datatype-pair configuration for combine/compression.

    Attributes:
        uncompressed_dtype: the in-memory operand dtype.
        compressed_dtype: the on-wire / compressed-operand dtype.
        supported_funcs: reduction functions this pair supports.
        arith_is_compressed: if True, reductions run in the compressed dtype
            (reference: ``arith_is_compressed`` bit choosing which lane feeds
            the reduce plugin).
    """

    uncompressed_dtype: np.dtype
    compressed_dtype: np.dtype
    supported_funcs: tuple[ReduceFunc, ...] = (
        ReduceFunc.SUM, ReduceFunc.MAX, ReduceFunc.MIN, ReduceFunc.PROD)
    arith_is_compressed: bool = False
    # Block-scaled quantized wire (accl_tpu/quant.py): >0 = the wire
    # carries per-block scale headers with this many elements per scale
    # block, and ETH_COMPRESSED emissions quantize/dequantize instead of
    # casting. 0 = plain dtype narrowing (the default). The driver
    # derives a block-scaled config per call (dataclasses.replace), so
    # the registry entries stay plain.
    quant_block: int = 0

    @property
    def uncompressed_elem_bytes(self) -> int:
        return int(self.uncompressed_dtype.itemsize)

    @property
    def compressed_elem_bytes(self) -> int:
        return int(self.compressed_dtype.itemsize)

    @property
    def elem_ratio(self) -> int:
        """How many compressed elements per uncompressed element (always 1
        elementwise; ratio of bytes drives wire savings)."""
        return 1

    @property
    def is_compressing(self) -> bool:
        return self.uncompressed_dtype != self.compressed_dtype

    @property
    def block_scaled(self) -> bool:
        """True when ETH_COMPRESSED wire traffic under this config is
        block-scale quantized rather than plainly narrowed."""
        return self.quant_block > 0

    def wire_dtype(self, compression: Compression) -> np.dtype:
        """Dtype that actually travels on the fabric for this call."""
        if compression & Compression.ETH_COMPRESSED:
            return self.compressed_dtype
        return self.uncompressed_dtype


def _mk(u: str, c: str, **kw) -> ArithConfig:
    return ArithConfig(np.dtype(u), np.dtype(c), **kw)


# Default registry keyed by (uncompressed, compressed) numpy dtype names.
# Parity: reference ACCL_DEFAULT_ARITH_CONFIG (accl.py:227-246) covers
# {f32,f64,i32,i64,f16} same-dtype plus (f32,f16) mixed. We add bf16 (the
# TPU-native half type) and int8/fp8-ready entries for quantized wire lanes.
DEFAULT_ARITH_CONFIGS: dict[tuple[str, str], ArithConfig] = {
    ("float32", "float32"): _mk("float32", "float32"),
    ("float64", "float64"): _mk("float64", "float64"),
    ("int32", "int32"): _mk("int32", "int32"),
    ("int64", "int64"): _mk("int64", "int64"),
    ("float16", "float16"): _mk("float16", "float16"),
    ("float32", "float16"): _mk("float32", "float16"),
    ("int8", "int8"): _mk("int8", "int8"),
    # int8 quantized wire lane: f32 in memory, int8 on the wire —
    # intended for the BLOCK_SCALED path (per-block absmax scales make
    # int8 wire numerically meaningful; a plain astype narrowing to
    # int8 truncates and is almost never what a caller wants)
    ("float32", "int8"): _mk("float32", "int8"),
}

try:  # bfloat16 comes from ml_dtypes (always present with jax)
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
    DEFAULT_ARITH_CONFIGS[("bfloat16", "bfloat16")] = ArithConfig(_BF16, _BF16)
    DEFAULT_ARITH_CONFIGS[("float32", "bfloat16")] = ArithConfig(
        np.dtype("float32"), _BF16)
    # fp8 quantized wire lane (EQuARX-style): fp32 in memory, e4m3 on the
    # wire/compressed operands; arithmetic always in fp32
    _F8 = np.dtype(ml_dtypes.float8_e4m3fn)
    DEFAULT_ARITH_CONFIGS[("float8_e4m3fn", "float8_e4m3fn")] = ArithConfig(
        _F8, _F8)
    DEFAULT_ARITH_CONFIGS[("float32", "float8_e4m3fn")] = ArithConfig(
        np.dtype("float32"), _F8)
    # e5m2: the wide-dynamic-range fp8 flavor (2 mantissa bits, inf/NaN)
    _F8W = np.dtype(ml_dtypes.float8_e5m2)
    DEFAULT_ARITH_CONFIGS[("float8_e5m2", "float8_e5m2")] = ArithConfig(
        _F8W, _F8W)
    DEFAULT_ARITH_CONFIGS[("float32", "float8_e5m2")] = ArithConfig(
        np.dtype("float32"), _F8W)
except ImportError:  # pragma: no cover
    pass


def resolve_arith_config(
    dtypes: set[np.dtype] | frozenset[np.dtype],
    registry: dict[tuple[str, str], ArithConfig] | None = None,
) -> ArithConfig:
    """Resolve the dtype set of a call's operands to an ArithConfig.

    Mirrors the reference's ``prepare_call`` resolution (accl.py:528-592):
    a single dtype maps to the same-dtype config; a {wide, narrow} pair maps
    to the mixed config with per-operand compression flags decided by the
    caller.
    """
    registry = registry if registry is not None else DEFAULT_ARITH_CONFIGS
    names = sorted({np.dtype(d).name for d in dtypes})
    if len(names) == 1:
        key = (names[0], names[0])
    elif len(names) == 2:
        # wider dtype is "uncompressed"; try both orders
        a, b = names
        if (a, b) in registry:
            key = (a, b)
        elif (b, a) in registry:
            key = (b, a)
        else:
            raise KeyError(f"no arithmetic config for dtype pair {names}")
    else:
        raise ValueError(f"calls may mix at most 2 dtypes, got {names}")
    if key not in registry:
        raise KeyError(f"no arithmetic config for dtype pair {key}")
    return registry[key]
