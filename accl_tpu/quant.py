"""Block-scaled quantized wire codec (EQuARX-style, arXiv 2506.17615).

The eth-compression lane's second gear: where plain dtype narrowing
(``Compression.ETH_COMPRESSED``) casts every wire element to the
compressed dtype, **block-scaled quantization**
(``Compression.BLOCK_SCALED``) sends each segment as a compact header +
one f32 scale per ``block`` elements + the fp8/int8 payload, recovering
most of the narrow dtype's lost dynamic range: every block is quantized
against its own absmax, so a segment mixing tiny gradients with large
ones keeps ~2-3 effective extra bits over a single global cast.

Wire layout of one block-scaled segment payload (rides the ordinary eth
frame — the payload checksum covers header + scales + data, so a corrupt
SCALE recovers exactly like a corrupt payload byte):

    magic  u8   (0xB5 — malformed-payload fail-fast, second line behind
                 the frame checksum)
    qcode  u8   (DTYPE_CODES code of the quantized payload dtype)
    block  u16  (elements per scale block)
    count  u32  (payload element count)
    scales f32[ceil(count/block)]
    data   qdtype[count]

The payload is SELF-DESCRIBING: the receiver dequantizes from the header
alone, so the block size is a per-sender runtime choice (tuner-
recommended) that never needs wire-level agreement — only the
BLOCK_SCALED compression flag in the call descriptor does, like every
other compression flag.

Quantization semantics (the numpy REFERENCE — ``native/
combine_kernels.c`` carries compiled twins held BIT-IDENTICAL by
tests/test_combine_native.py, so serial/streamed/native-vs-numpy
differentials all agree):

* per block: ``amax = max(|x|)`` (NaN-propagating), ``scale = amax /
  qmax`` clamped to 1.0 unless positive, normal and finite;
* quantize: ``q = cast(x * (1/scale))`` — fp8 casts follow ml_dtypes
  (round-to-nearest-even, e4m3fn overflows to NaN, e5m2 to inf); int8
  rounds half-to-even, clips to [-127, 127], and quantizes non-finite
  values to 0;
* dequantize: ``x' = float32(q) * scale`` — one f32 rounding;
* the fused combine step is ``func(other, dequant(q))`` with all
  arithmetic in f32 (widen-accumulate): per-hop error is bounded by one
  quantization of the travelling partial, never compounding through the
  accumulator.

Error model: for fp8-e4m3 the per-element dequantization error is at
most ``amax(block) * 2^-4 / (1 - 2^-4)`` (half-ulp at the block scale);
int8 bounds at ``amax/254``. A W-rank ring allreduce requantizes the
travelling partial W-2 times plus the phase-2 relay, so end-to-end
error is ≤ ``(W) * eps_q * max|partial|`` — linear in hops because
accumulation stays f32 (docs/ARCHITECTURE.md, "Quantized wire").
"""

from __future__ import annotations

import struct

import numpy as np

from .constants import ReduceFunc
from .tracing import METRICS

__all__ = [
    "MAGIC", "HDR_BYTES", "MIN_BLOCK", "MAX_BLOCK", "DEFAULT_BLOCK",
    "is_quantizable", "packed_nbytes", "seg_elems", "quantize_packed",
    "dequantize_packed", "dequant_combine_packed", "QuantFormatError",
]

MAGIC = 0xB5
_HDR = struct.Struct("<BBHI")       # magic, qcode, block, count
HDR_BYTES = _HDR.size               # 8
# Block-size envelope: segmentation reserves scale overhead for the
# SMALLEST legal block (4 bytes per 32 elements = 1/8 byte/elem), so the
# packed segment fits the rx buffer for ANY runtime block choice and the
# compiled-plan cache never keys on the block size.
MIN_BLOCK = 32
MAX_BLOCK = 4096
DEFAULT_BLOCK = 128

_FLT_MIN = np.float32(1.1754943508222875e-38)   # smallest normal f32

# quantizable wire dtypes -> (protocol code, qmax). Codes are
# emulator/protocol.py DTYPE_CODES values, listed literally like
# native_combine's table so importing this module never touches the
# emulator package (test_quantize pins them against protocol's).
_QCODES = {"int8": 6, "float8_e4m3fn": 8, "float8_e5m2": 9}
_QMAX = {"int8": 127.0, "float8_e4m3fn": 448.0, "float8_e5m2": 57344.0}

_NP_FUNCS = {
    ReduceFunc.SUM: np.add,
    ReduceFunc.MAX: np.maximum,
    ReduceFunc.MIN: np.minimum,
    ReduceFunc.PROD: np.multiply,
}


class QuantFormatError(ValueError):
    """A block-scaled payload failed structural validation (bad magic,
    dtype code, block, count or byte length). Normally unreachable —
    the frame checksum rejects corruption before decode — so this is
    the typed second line for checksum-off worlds."""


def is_quantizable(dtype) -> bool:
    """True when ``dtype`` can be a block-scaled wire dtype."""
    return np.dtype(dtype).name in _QCODES


def _qdtype_of(code: int) -> np.dtype:
    for name, c in _QCODES.items():
        if c == code:
            if name == "int8":
                return np.dtype(np.int8)
            import ml_dtypes
            return np.dtype(getattr(ml_dtypes, name))
    raise QuantFormatError(f"unknown quantized dtype code {code}")


def clamp_block(block: int) -> int:
    """Clamp a requested block size into the legal envelope, rounded
    DOWN to a power of two — the call descriptor carries the block as a
    log2 nibble (protocol.pack_call's qblock byte), so every tier
    reconstructs the identical value."""
    b = max(MIN_BLOCK, min(MAX_BLOCK, int(block)))
    return 1 << (b.bit_length() - 1)


def n_blocks(count: int, block: int) -> int:
    return -(-int(count) // int(block))


def packed_nbytes(count: int, block: int, qbytes: int = 1) -> int:
    """Exact wire bytes of one packed segment."""
    return HDR_BYTES + 4 * n_blocks(count, block) + count * qbytes


def seg_elems(max_segment_size: int, qbytes: int = 1) -> int:
    """Elements per wire segment for a block-scaled send, independent of
    the runtime block choice: reserves header + worst-case (MIN_BLOCK)
    scale overhead so ``packed_nbytes(n, block) <= max_segment_size``
    for every legal block. The twin of moveengine._seg_elems's
    compressed-elem division, kept here so the planner and the device
    cannot drift."""
    # 4 bytes of scale per MIN_BLOCK elems = 8*qbytes+1 eighth-bytes per
    # elem; 12 covers the header plus the final partial block's scale
    return max(1, 8 * (int(max_segment_size) - HDR_BYTES - 4)
               // (8 * int(qbytes) + 1))


# -- metrics (module counters + collector: per-segment registry incs are
#    the storm-shaped cost the daemon collectors avoid) ---------------------

_tx = [0, 0, 0]      # [segments, blocks, wire bytes saved]
_rx = [0, 0]         # [segments, blocks]
_calls = [0, 0]      # [native, numpy] codec calls


class _Collector:
    pass


_collector_owner = _Collector()


def _collector_rows(_owner):
    yield ("counter", "quant_segments_total", {"dir": "tx"}, _tx[0])
    yield ("counter", "quant_segments_total", {"dir": "rx"}, _rx[0])
    yield ("counter", "quant_blocks_total", {"dir": "tx"}, _tx[1])
    yield ("counter", "quant_blocks_total", {"dir": "rx"}, _rx[1])
    yield ("counter", "quant_wire_bytes_saved_total", {}, _tx[2])
    yield ("counter", "quant_codec_calls_total", {"path": "native"},
           _calls[0])
    yield ("counter", "quant_codec_calls_total", {"path": "numpy"},
           _calls[1])


METRICS.register_collector(_collector_owner, _collector_rows)


def counters() -> dict:
    """Snapshot for tests/benches: tx/rx segment+block counts and wire
    bytes saved so far in this process."""
    return {"tx_segments": _tx[0], "tx_blocks": _tx[1],
            "wire_bytes_saved": _tx[2], "rx_segments": _rx[0],
            "rx_blocks": _rx[1], "native_calls": _calls[0],
            "numpy_calls": _calls[1]}


# -- native dispatch --------------------------------------------------------

def _native():
    """The compiled codec module (native/combine_kernels.c) or None —
    resolved through native_combine's loader so both compiled lanes
    share one .so, one build path and one enable knob."""
    from . import native_combine
    lib = native_combine.module()
    # older prebuilt .so without the bs entries degrades to numpy
    if lib is not None and hasattr(lib, "bs_quantize"):
        return lib
    return None


# -- numpy reference --------------------------------------------------------

def _np_scales(x: np.ndarray, block: int, qmax: float) -> np.ndarray:
    n = x.size
    nb = n_blocks(n, block)
    a = np.abs(x)
    if n != nb * block:
        a = np.concatenate([a, np.zeros(nb * block - n, np.float32)])
    amax = a.reshape(nb, block).max(axis=1)
    with np.errstate(invalid="ignore", over="ignore"):
        s = (amax / np.float32(qmax)).astype(np.float32)
        good = (s >= _FLT_MIN) & (s < np.inf)
    return np.where(good, s, np.float32(1.0))


def _np_quantize(x: np.ndarray, qdtype: np.dtype, block: int
                 ) -> tuple[np.ndarray, np.ndarray]:
    """(scales f32[nb], q qdtype[n]) — the reference the compiled
    kernel is held bit-identical to."""
    s = _np_scales(x, block, _QMAX[qdtype.name])
    inv = np.float32(1.0) / s
    with np.errstate(invalid="ignore", over="ignore"):
        v = x * np.repeat(inv, block)[:x.size]
        if qdtype.name == "int8":
            q = np.where(np.isfinite(v),
                         np.clip(np.rint(v), -127, 127), 0).astype(np.int8)
        else:
            q = v.astype(qdtype)
    return s, q


def _np_dequant(scales: np.ndarray, q: np.ndarray, block: int
                ) -> np.ndarray:
    with np.errstate(invalid="ignore", over="ignore"):
        return (q.astype(np.float32)
                * np.repeat(scales, block)[:q.size]).astype(np.float32)


# -- packed codec (the executor's entry points) -----------------------------

def quantize_packed(x: np.ndarray, qdtype, block: int) -> np.ndarray:
    """Pack one segment: f32 operand -> owned uint8 array
    [header | scales | payload]. ``x`` must be 1-D contiguous float32
    (the executor's combine-result shape)."""
    qdtype = np.dtype(qdtype)
    code = _QCODES[qdtype.name]
    block = clamp_block(block)
    n = int(x.size)
    nb = n_blocks(n, block)
    out = np.empty(HDR_BYTES + 4 * nb + n, np.uint8)
    _HDR.pack_into(out, 0, MAGIC, code, block, n)
    scales = out[HDR_BYTES:HDR_BYTES + 4 * nb].view(np.float32)
    qview = out[HDR_BYTES + 4 * nb:]
    lib = _native()
    if lib is not None and x.flags.c_contiguous:
        lib.bs_quantize(code, block, x, scales, qview)
        _calls[0] += 1
    else:
        s, q = _np_quantize(np.ascontiguousarray(x, np.float32), qdtype,
                            block)
        scales[:] = s
        qview[:] = q.view(np.uint8)
        _calls[1] += 1
    _tx[0] += 1
    _tx[1] += nb
    _tx[2] += max(0, n * 4 - out.nbytes)
    return out


def _parse(payload, expect_count: int):
    """Validate + split one packed segment -> (qcode, block, scales
    bytes-view, q bytes-view, n, nb)."""
    mv = memoryview(payload)
    if mv.ndim != 1 or mv.itemsize != 1:
        mv = mv.cast("B")
    if len(mv) < HDR_BYTES:
        raise QuantFormatError(
            f"block-scaled payload shorter than its header "
            f"({len(mv)} B)")
    magic, code, block, n = _HDR.unpack_from(mv, 0)
    if magic != MAGIC:
        raise QuantFormatError(
            f"bad block-scaled magic {magic:#x} (want {MAGIC:#x})")
    if not MIN_BLOCK <= block <= MAX_BLOCK:
        raise QuantFormatError(f"illegal block size {block}")
    if expect_count is not None and n != expect_count:
        raise QuantFormatError(
            f"payload carries {n} elements, move expects {expect_count}")
    qdtype = _qdtype_of(code)
    nb = n_blocks(n, block)
    want = HDR_BYTES + 4 * nb + n * qdtype.itemsize
    if len(mv) != want:
        raise QuantFormatError(
            f"payload is {len(mv)} B, layout wants {want} B")
    return (code, qdtype, block,
            mv[HDR_BYTES:HDR_BYTES + 4 * nb],
            mv[HDR_BYTES + 4 * nb:want], n, nb)


def dequantize_packed(payload, expect_count: int | None = None
                      ) -> np.ndarray:
    """Unpack one segment to a fresh f32 array."""
    code, qdtype, block, smv, qmv, n, nb = _parse(payload, expect_count)
    out = np.empty(n, np.float32)
    lib = _native()
    if lib is not None:
        lib.bs_dequant(code, block, smv, qmv, out)
        _calls[0] += 1
    else:
        out[:] = _np_dequant(np.frombuffer(smv, np.float32),
                             np.frombuffer(qmv, qdtype), block)
        _calls[1] += 1
    _rx[0] += 1
    _rx[1] += nb
    return out


def dequant_combine_packed(payload, other: np.ndarray, func: ReduceFunc,
                           out: np.ndarray | None = None,
                           expect_count: int | None = None) -> np.ndarray:
    """The fused dequant -> accumulate step: ``out = func(other,
    dequant(payload))`` with f32 accumulation, one compiled pass when
    the native codec is available (GIL released at segment sizes).
    ``other`` must be f32; ``out`` may alias neither input's memory in
    the numpy fallback sense (the executor passes arena scratch)."""
    code, qdtype, block, smv, qmv, n, nb = _parse(payload, expect_count)
    if other.size != n:
        raise QuantFormatError(
            f"combine operand has {other.size} elements, payload {n}")
    lib = _native()
    if (lib is not None and other.dtype == np.float32
            and other.flags.c_contiguous):
        if out is None:
            out = np.empty(n, np.float32)
        lib.bs_combine(int(func), code, block, smv, qmv, other, out)
        _calls[0] += 1
    else:
        v = _np_dequant(np.frombuffer(smv, np.float32),
                        np.frombuffer(qmv, qdtype), block)
        npf = _NP_FUNCS[ReduceFunc(func)]
        if out is None:
            out = npf(other.astype(np.float32, copy=False), v)
        else:
            npf(other.astype(np.float32, copy=False), v, out=out)
        _calls[1] += 1
    _rx[0] += 1
    _rx[1] += nb
    return out
