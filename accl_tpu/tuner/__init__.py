"""Autotuner: cost-model + measurement-driven algorithm & segment-size
selection.

Three layers (docs/TUNER.md):

* :mod:`~accl_tpu.tuner.cost` — alpha-beta analytic cost models per
  (collective, algorithm) over a :class:`Topology` descriptor each device
  backend exposes (``Device.topology()``);
* :mod:`~accl_tpu.tuner.tuner` — the thread-safe :class:`Tuner` resolving
  ``AUTO`` per (op, world_size, nbytes-bucket), refined online from
  retire-time measurements, with epsilon-greedy exploration and segment-
  size recommendation;
* :mod:`~accl_tpu.tuner.cache` — versioned JSON tuning tables
  (``ACCL_TPU_TUNING_CACHE``) produced by ``python -m benchmarks --tune``.

Attach with ``ACCL(device, comm, tuner=Tuner())``.
"""

from . import cache
from .cost import Topology, predict_us, rank_algorithms, \
    recommend_segment_size
from .tuner import Tuner, nbytes_bucket

__all__ = ["Topology", "Tuner", "cache", "nbytes_bucket", "predict_us",
           "rank_algorithms", "recommend_segment_size"]
