"""Alpha-beta analytic cost models per (collective, algorithm).

The model follows the classic Hockney formulation the collective-selection
literature keys on (NCCL's tuner, EQuARX's size/topology-keyed XLA
decisions — PAPERS.md): a move costs ``alpha`` microseconds of fixed
per-hop overhead (software expansion, tag matching, rendezvous) plus its
wire bytes over a ``beta`` GB/s link. Every formula below models OUR move
expansions (moveengine.py), not textbook ideals:

* ring/daisy algorithms serialize ``W-1`` dependency hops, each paying a
  full ``alpha`` — cheap per-hop payloads, expensive in hop count;
* direct (round-robin) algorithms pay one ``alpha`` of critical-path
  latency but funnel ``W-1`` payloads through one endpoint, modeled with
  an ``incast`` congestion factor on the wire term;
* the fused ring allreduce does ``2(W-1)`` blocking steps of ``n/W``
  bytes; the non-fused variant is a daisy-chain reduce of the full
  payload plus a broadcast whose root-side sends are non-blocking
  (expand_broadcast marks them ``blocking=False``) and therefore overlap
  down to one ``alpha`` plus serialized injection.

The crossovers these shapes produce are the point of the subsystem:
latency-bound (small ``n``) calls favor few-alpha algorithms, bandwidth-
bound (large ``n``) calls favor low-wire-volume ones. Absolute numbers
only need to be *ordered* correctly per topology tier; the online
measurement path (tuner.py) refines where the model is wrong.

``nbytes`` everywhere is the call's ``count * uncompressed_elem_bytes`` —
the same convention the driver computes, so model and measurement index
the same quantity (NOTE: for chunked ops — gather/allgather/scatter/
reduce_scatter/alltoall — ``count`` is the per-rank chunk, so ``nbytes``
is chunk bytes, not aggregate payload).
"""

from __future__ import annotations

import dataclasses
import math

from ..constants import CollectiveAlgorithm, VALID_ALGORITHMS

__all__ = ["Topology", "predict_us", "rank_algorithms",
           "recommend_segment_size"]


@dataclasses.dataclass(frozen=True)
class Topology:
    """Link-level descriptor of one fabric tier.

    Each :class:`~accl_tpu.device.base.Device` backend exposes its own via
    ``Device.topology()``; the numbers are calibrated order-of-magnitude
    figures for that tier (thread handoff vs socket RPC vs ICI hop), good
    enough to order algorithms — measurement refines the rest.
    """

    world_size: int = 0        # ranks on the fabric (0 = not yet known)
    alpha_us: float = 50.0     # per-hop latency + per-move software cost
    beta_gbps: float = 1.0     # per-link bandwidth, GB/s
    incast: float = 2.0        # fan-in congestion factor at a hot receiver
    tier: str = "generic"
    # Segment-pipeline overlap depth: how many segments the dataplane
    # keeps concurrently in flight (recv-match / combine / relay of
    # different lanes). 1.0 = store-and-forward (no overlap). With depth
    # d, per-segment alpha amortizes across the lanes in flight, so the
    # *effective* overhead of choosing smaller segments shrinks by ~d —
    # equivalently the pipeline sustains an effective beta close to the
    # wire beta down to segments d× smaller (see recommend_segment_size).
    pipeline_depth: float = 1.0

    def wire_us(self, nbytes: float) -> float:
        """Microseconds to move ``nbytes`` over one link."""
        return float(nbytes) / (self.beta_gbps * 1e3)  # GB/s == bytes/us*1e3


# -- per-(op, algorithm) models ---------------------------------------------
# Each takes (topo, W, nbytes) and returns predicted microseconds.

def _ring_chain(topo: Topology, w: int, nbytes: float) -> float:
    """W-1 serialized hops of the full per-hop payload (gather/allgather
    relays, daisy-chain reduce, ring reduce-scatter)."""
    return (w - 1) * (topo.alpha_us + topo.wire_us(nbytes))


def _direct_fanin(topo: Topology, w: int, nbytes: float) -> float:
    """One hop of latency; W-1 payloads squeezed through one endpoint."""
    return topo.alpha_us + topo.incast * (w - 1) * topo.wire_us(nbytes)


def _bcast_rr(topo: Topology, w: int, nbytes: float) -> float:
    """Root's sends are non-blocking (one alpha on the critical path) but
    serialize at its injection port."""
    return topo.alpha_us + (w - 1) * topo.wire_us(nbytes)


def _bcast_tree(topo: Topology, w: int, nbytes: float) -> float:
    """ceil(log2 W) dependent rounds, full payload each."""
    rounds = max(1, math.ceil(math.log2(max(w, 2))))
    return rounds * (topo.alpha_us + topo.wire_us(nbytes))


def _allreduce_fused(topo: Topology, w: int, nbytes: float) -> float:
    """2(W-1) blocking fused-recv-reduce/relay steps of n/W bytes each."""
    return 2 * (w - 1) * (topo.alpha_us + topo.wire_us(nbytes / w))


def _allreduce_nonfused(topo: Topology, w: int, nbytes: float) -> float:
    """Daisy-chain reduce to rank 0 + round-robin bcast of the result."""
    return _ring_chain(topo, w, nbytes) + _bcast_rr(topo, w, nbytes)


_A = CollectiveAlgorithm
_MODELS = {
    ("bcast", _A.ROUND_ROBIN): _bcast_rr,
    ("bcast", _A.TREE): _bcast_tree,
    ("scatter", _A.ROUND_ROBIN): _bcast_rr,   # strided rr sends from root
    ("gather", _A.RING): _ring_chain,
    ("gather", _A.ROUND_ROBIN): _direct_fanin,
    ("reduce", _A.RING): _ring_chain,
    ("reduce", _A.ROUND_ROBIN): _direct_fanin,
    ("allgather", _A.RING): _ring_chain,
    ("allgather", _A.ROUND_ROBIN): _direct_fanin,
    # RING and FUSED_RING share one expansion (expand_allreduce_ring);
    # the epsilon nudge makes AUTO surface the canonical FUSED_RING name
    ("allreduce", _A.RING): lambda t, w, n: 1.0001 * _allreduce_fused(
        t, w, n),
    ("allreduce", _A.FUSED_RING): _allreduce_fused,
    ("allreduce", _A.NON_FUSED): _allreduce_nonfused,
    ("reduce_scatter", _A.RING): _ring_chain,
}


def predict_us(op: str, algorithm: CollectiveAlgorithm, topo: Topology,
               nbytes: int, world_size: int | None = None) -> float:
    """Predicted call time in microseconds for one (op, algorithm) pair."""
    w = world_size if world_size is not None else topo.world_size
    if w <= 1:
        return 0.0
    model = _MODELS.get((op, _A(algorithm)))
    if model is None:
        raise KeyError(f"no cost model for ({op}, "
                       f"{_A(algorithm).name})")
    return model(topo, w, float(nbytes))


def rank_algorithms(op: str, topo: Topology, nbytes: int,
                    world_size: int | None = None
                    ) -> list[tuple[CollectiveAlgorithm, float]]:
    """Every legal algorithm of ``op`` with its predicted cost, cheapest
    first. Ties break toward the lower enum value (deterministic across
    runs and ranks — every rank of a collective must pick the same
    algorithm from the same inputs)."""
    valid = VALID_ALGORITHMS.get(op)
    if not valid:
        return []
    scored = [(a, predict_us(op, a, topo, nbytes, world_size))
              for a in sorted(valid)]
    scored.sort(key=lambda p: (p[1], int(p[0])))
    return scored


def recommend_segment_size(topo: Topology, preferred: int,
                           overhead_fraction: float = 0.1,
                           floor: int = 4096,
                           overlap_depth: float | None = None) -> int:
    """Smallest power-of-two segment whose per-segment ``alpha`` overhead
    is at most ``overhead_fraction`` of its wire time, clamped to
    ``[floor, preferred]``.

    ``preferred`` is the backend's ``preferred_segment_size()`` — the
    largest segment it can accept (rx-buffer bound on the emulator tiers).
    High-alpha fabrics want segments as large as allowed; low-alpha/high-
    beta fabrics can afford smaller segments (better pipelining overlap,
    reference dma_mover segmentation) without drowning in per-segment cost.

    Overlap-aware effective beta: with a segment-streamed dataplane
    (``overlap_depth``, defaulting to ``topo.pipeline_depth``) the
    per-segment alpha of ~depth lanes is paid concurrently, so the
    *effective* per-segment overhead is ``alpha/depth`` — the pipeline
    sustains close to wire beta down to segments depth× smaller. Smaller
    segments in turn deepen the recv→combine→relay overlap, which is
    exactly what the streamed executor converts into throughput; a
    store-and-forward engine (depth 1) keeps the conservative sizing.
    """
    depth = max(1.0, (topo.pipeline_depth if overlap_depth is None
                      else overlap_depth))
    if preferred <= floor:
        return preferred
    target = (topo.alpha_us / depth) / overhead_fraction \
        * topo.beta_gbps * 1e3
    seg = 1 << max(1, math.ceil(math.log2(max(target, 1.0))))
    return max(floor, min(seg, preferred))
