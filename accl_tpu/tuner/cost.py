"""Alpha-beta analytic cost models per (collective, algorithm).

The model follows the classic Hockney formulation the collective-selection
literature keys on (NCCL's tuner, EQuARX's size/topology-keyed XLA
decisions — PAPERS.md): a move costs ``alpha`` microseconds of fixed
per-hop overhead (software expansion, tag matching, rendezvous) plus its
wire bytes over a ``beta`` GB/s link. Every formula below models OUR move
expansions (moveengine.py), not textbook ideals:

* ring/daisy algorithms serialize ``W-1`` dependency hops, each paying a
  full ``alpha`` — cheap per-hop payloads, expensive in hop count;
* direct (round-robin) algorithms pay one ``alpha`` of critical-path
  latency but funnel ``W-1`` payloads through one endpoint, modeled with
  an ``incast`` congestion factor on the wire term;
* the fused ring allreduce does ``2(W-1)`` blocking steps of ``n/W``
  bytes; the non-fused variant is a daisy-chain reduce of the full
  payload plus a broadcast whose root-side sends are non-blocking
  (expand_broadcast marks them ``blocking=False``) and therefore overlap
  down to one ``alpha`` plus serialized injection.

The crossovers these shapes produce are the point of the subsystem:
latency-bound (small ``n``) calls favor few-alpha algorithms, bandwidth-
bound (large ``n``) calls favor low-wire-volume ones. Absolute numbers
only need to be *ordered* correctly per topology tier; the online
measurement path (tuner.py) refines where the model is wrong.

``nbytes`` everywhere is the call's ``count * uncompressed_elem_bytes`` —
the same convention the driver computes, so model and measurement index
the same quantity (NOTE: for chunked ops — gather/allgather/scatter/
reduce_scatter/alltoall — ``count`` is the per-rank chunk, so ``nbytes``
is chunk bytes, not aggregate payload).
"""

from __future__ import annotations

import dataclasses
import math

from ..constants import CollectiveAlgorithm, VALID_ALGORITHMS

__all__ = ["Topology", "predict_us", "rank_algorithms",
           "recommend_segment_size", "LEGACY_ALGORITHM_PAIRS",
           "predict_quantized_us", "rank_wire", "wire_byte_ratio",
           "predict_alltoallv_us", "WIRE_PRICED_OPS"]


# (op, algorithm) pairs every execution tier has always implemented —
# the reference-derived ring/round-robin families plus the bcast tree.
# A tier whose peer engine may lack the log-depth family (the socket
# client can face the native C++ daemon, which validates and expands
# only these) advertises this set as Topology.supported so AUTO never
# resolves to an algorithm the peer would reject; explicit selectors
# still pass through (and fail loudly at the peer's validation).
_A = CollectiveAlgorithm
LEGACY_ALGORITHM_PAIRS: frozenset = frozenset({
    ("bcast", _A.ROUND_ROBIN), ("bcast", _A.TREE),
    ("scatter", _A.ROUND_ROBIN),
    ("gather", _A.RING), ("gather", _A.ROUND_ROBIN),
    ("reduce", _A.RING), ("reduce", _A.ROUND_ROBIN),
    ("allgather", _A.RING), ("allgather", _A.ROUND_ROBIN),
    ("allreduce", _A.RING), ("allreduce", _A.FUSED_RING),
    ("allreduce", _A.NON_FUSED),
    ("reduce_scatter", _A.RING),
})


@dataclasses.dataclass(frozen=True)
class Topology:
    """Link-level descriptor of one fabric tier.

    Each :class:`~accl_tpu.device.base.Device` backend exposes its own via
    ``Device.topology()``; the numbers are calibrated order-of-magnitude
    figures for that tier (thread handoff vs socket RPC vs ICI hop), good
    enough to order algorithms — measurement refines the rest.
    """

    world_size: int = 0        # ranks on the fabric (0 = not yet known)
    alpha_us: float = 50.0     # per-hop latency + per-move software cost
    beta_gbps: float = 1.0     # per-link bandwidth, GB/s
    incast: float = 2.0        # fan-in congestion factor at a hot receiver
    tier: str = "generic"
    # Segment-pipeline overlap depth: how many segments the dataplane
    # keeps concurrently in flight (recv-match / combine / relay of
    # different lanes). 1.0 = store-and-forward (no overlap). With depth
    # d, per-segment alpha amortizes across the lanes in flight, so the
    # *effective* overhead of choosing smaller segments shrinks by ~d —
    # equivalently the pipeline sustains an effective beta close to the
    # wire beta down to segments d× smaller (see recommend_segment_size).
    pipeline_depth: float = 1.0
    # (op, algorithm) pairs this tier's execution engines implement;
    # None = everything in VALID_ALGORITHMS. AUTO resolution
    # (rank_algorithms / Tuner) never selects outside this set — the
    # socket tier advertises LEGACY_ALGORITHM_PAIRS because its peer may
    # be the native daemon, which lacks the log-depth family.
    supported: frozenset | None = None
    # Quantized-wire pricing (accl_tpu/quant.py; ACCL+ arXiv 2312.11742
    # frames compression plugins exactly this way — a beta multiplier
    # bought with compute): throughput of the tier's quantize/dequantize
    # passes in GB/s of UNCOMPRESSED payload (the gamma term's
    # denominator) and the fixed per-call cost of arming the quantized
    # lane (scale/header bookkeeping — what keeps small latency-bound
    # calls on the full-precision wire).
    quant_gbps: float = 6.0
    quant_alpha_us: float = 15.0

    def wire_us(self, nbytes: float) -> float:
        """Microseconds to move ``nbytes`` over one link."""
        return float(nbytes) / (self.beta_gbps * 1e3)  # GB/s == bytes/us*1e3


# -- per-(op, algorithm) models ---------------------------------------------
# Each takes (topo, W, nbytes) and returns predicted microseconds.

def _ring_chain(topo: Topology, w: int, nbytes: float) -> float:
    """W-1 serialized hops of the full per-hop payload (gather/allgather
    relays, daisy-chain reduce, ring reduce-scatter)."""
    return (w - 1) * (topo.alpha_us + topo.wire_us(nbytes))


def _direct_fanin(topo: Topology, w: int, nbytes: float) -> float:
    """One hop of latency; W-1 payloads squeezed through one endpoint."""
    return topo.alpha_us + topo.incast * (w - 1) * topo.wire_us(nbytes)


def _bcast_rr(topo: Topology, w: int, nbytes: float) -> float:
    """Root's sends are non-blocking (one alpha on the critical path) but
    serialize at its injection port."""
    return topo.alpha_us + (w - 1) * topo.wire_us(nbytes)


def _bcast_tree(topo: Topology, w: int, nbytes: float) -> float:
    """ceil(log2 W) dependent rounds, full payload each."""
    rounds = max(1, math.ceil(math.log2(max(w, 2))))
    return rounds * (topo.alpha_us + topo.wire_us(nbytes))


def _allreduce_fused(topo: Topology, w: int, nbytes: float) -> float:
    """2(W-1) blocking fused-recv-reduce/relay steps of n/W bytes each."""
    return 2 * (w - 1) * (topo.alpha_us + topo.wire_us(nbytes / w))


def _allreduce_nonfused(topo: Topology, w: int, nbytes: float) -> float:
    """Daisy-chain reduce to rank 0 + round-robin bcast of the result."""
    return _ring_chain(topo, w, nbytes) + _bcast_rr(topo, w, nbytes)


# -- log-depth family (modeled on OUR expansions, moveengine.py) ------------
#
# Alpha terms: ceil(log2 W) dependency rounds (+2 barrier phases for the
# non-power-of-2 vrank fold). Wire terms: the same aggregate volume as
# the ring algorithms, but paid in per-round bursts to a DIFFERENT
# partner each round, where the ring trickles fixed-size chunks to one
# fixed neighbor — the streamed executor's per-peer egress, the arrival
# listener, and the fabric coalescing path all sustain a lower effective
# beta on the bursty pattern, and the halving/doubling phases split the
# ring's single fused recv-reduce-relay move into separate recv-reduce
# and send moves (twice the per-byte move software cost). The factors
# below fold that into the wire term; the emulator benchmark ladder
# (benchmarks/algorithms.py) measures the resulting crossover, and the
# online path (tuner.py) refines wherever a real host disagrees.
_RD_WIRE_FACTOR = 1.3       # doubling allgather relays (recv + re-send)
_RD_FUSE_FACTOR = 1.5       # halving phases (unfused recv-reduce + send)
# Rabenseifner's rounds are PAIRWISE-SYNCHRONIZED: every rank wakes and
# issues a send + a separate fused recv-reduce each round, where the
# chain algorithms keep one active hop at a time — per-round software
# cost runs ~1.4 alpha on the measured ladder. This keeps the few-move
# NON_FUSED variant the small-n winner (it measures 3-4x faster than
# Rabenseifner below ~4 KiB on the emulator tier) while Rabenseifner
# owns the mid band up to the ring crossover.
_RD_SYNC_FACTOR = 1.4


def _rd_rounds(w: int) -> int:
    """Pairwise-exchange rounds over p = 2^floor(log2 w) vranks."""
    return max(1, (max(w, 2)).bit_length() - 1)


def _rd_fold(w: int) -> float:
    """1.0 when the vrank fold-in/fold-out barrier phases exist."""
    return 0.0 if w & (w - 1) == 0 else 1.0


def _allgather_rd(topo: Topology, w: int, nbytes: float) -> float:
    """log2(p) exchange rounds moving (w-1)*n total; the fold ships the
    whole w*n result to extras in the post phase."""
    return (_rd_rounds(w) * topo.alpha_us
            + _RD_WIRE_FACTOR * (w - 1) * topo.wire_us(nbytes)
            + _rd_fold(w) * (2 * topo.alpha_us + w * topo.wire_us(nbytes)))


def _reduce_scatter_rh(topo: Topology, w: int, nbytes: float) -> float:
    """log2(p) halving rounds moving (w-1)*n total partials; the fold
    pre-phase ships extras' whole w*n input vectors."""
    return (_rd_rounds(w) * topo.alpha_us
            + _RD_FUSE_FACTOR * (w - 1) * topo.wire_us(nbytes)
            + _rd_fold(w) * (2 * topo.alpha_us
                             + (w + 1) * topo.wire_us(nbytes)))


def _allreduce_rd(topo: Topology, w: int, nbytes: float) -> float:
    """Rabenseifner: halving reduce-scatter + doubling allgather —
    2*log2(p) synchronized rounds at the fused ring's ~2n(w-1)/w wire
    volume."""
    return (_RD_SYNC_FACTOR * 2 * _rd_rounds(w) * topo.alpha_us
            + _RD_FUSE_FACTOR * 2 * (w - 1) / w * topo.wire_us(nbytes)
            + _rd_fold(w) * (2 * topo.alpha_us + 2 * topo.wire_us(nbytes)))


def _allgather_direct(topo: Topology, w: int, nbytes: float) -> float:
    """Direct fan-out allgather: one alpha of dependency depth, but OUR
    expansion has every rank burst-inject w-1 eager sends before any of
    its w-1 recvs can progress — the burst serializes in the executor
    ahead of recv-matching (unlike gather's direct variant, where the
    non-roots each issue a single send), modeled as a per-extra-send
    alpha fraction on top of the incast wire term."""
    return (topo.alpha_us * (1 + 0.4 * max(0, w - 2))
            + topo.incast * (w - 1) * topo.wire_us(nbytes))


# -- algorithm-less wire-priced ops (alltoall / alltoallv) ------------------
#
# Neither op has an algorithm axis (VALID_ALGORITHMS omits them; only
# AUTO is legal), but both still need a price so the WIRE decision
# ("auto" compress_dtype -> fp8 block-scaled vs full precision) can rank
# the quantized variant. The exchange is balanced across endpoints, so
# no incast factor applies; the round-robin step schedule pipelines in
# the streamed executor, so per-step software cost amortizes like the
# allgather burst model (0.4 alpha per extra step).

WIRE_PRICED_OPS = frozenset({"alltoall", "alltoallv"})


def _alltoall_us(topo: Topology, w: int, nbytes: float) -> float:
    """Balanced exchange, ``nbytes`` = per-pair chunk (the chunked-op
    convention): W-1 pipelined steps, W-1 chunks through this rank's
    injection port."""
    return (topo.alpha_us * (1 + 0.4 * max(0, w - 2))
            + (w - 1) * topo.wire_us(nbytes))


def _alltoallv_us(topo: Topology, w: int, nbytes: float) -> float:
    """Uneven exchange, ``nbytes`` = this rank's PORT bytes — the driver
    keys the wire decision on max(sum(send), sum(recv)) elements (the
    descriptor's ``count``), which is already the aggregate through the
    port, not a per-pair chunk. Vector-aware pricing (zero-peer alpha
    skipping) lives in :func:`predict_alltoallv_us`."""
    return (topo.alpha_us * (1 + 0.4 * max(0, w - 2))
            + topo.wire_us(nbytes))


def predict_alltoallv_us(topo: Topology, send_counts, recv_counts,
                         elem_bytes: int) -> float:
    """Per-rank price of one uneven exchange given its count vectors:
    one pipelined alpha per NONZERO peer interval (zero-count peers
    expand to no moves at all — the skew case this op exists for) plus
    this rank's port bytes (send and recv directions overlap on a
    full-duplex port, so the max of the two totals bounds the wire
    term). Deterministic in its inputs; the uneven-reshard fast path
    and the tuner's wire ranking share this one formula."""
    peers = (sum(1 for c in send_counts if c)
             + sum(1 for c in recv_counts if c))
    if peers == 0:
        return 0.0
    port_bytes = max(sum(send_counts), sum(recv_counts)) * elem_bytes
    return (topo.alpha_us * (1 + 0.4 * max(0, peers - 1))
            + topo.wire_us(port_bytes))


def _reduce_tree(topo: Topology, w: int, nbytes: float) -> float:
    """ceil(log2 W) dependent rounds, full payload each (the bcast-tree
    shape run in reverse, with the folds spread across internal nodes)."""
    return _bcast_tree(topo, w, nbytes)


def _gather_tree(topo: Topology, w: int, nbytes: float) -> float:
    """log-depth hop chain; the root still ingests all w-1 chunks, but
    spread over subtree-sized messages instead of the direct algorithm's
    w-1-way incast. Internal nodes store-and-forward their whole subtree
    (scratch write + re-send — an extra local pass the ring relay does
    not pay), the same re-read overhead as the doubling relays."""
    rounds = max(1, math.ceil(math.log2(max(w, 2))))
    return (rounds * topo.alpha_us
            + _RD_WIRE_FACTOR * (w - 1) * topo.wire_us(nbytes))


# -- N-tier hierarchical family (accl_tpu/hier) -----------------------------
#
# HIERARCHICAL is a DRIVER-level phase program over sub-communicators
# (hier/engine.py): e.g. allreduce = reduce-scatter descending the
# nest -> allreduce(top tier) -> allgather ascending. Its cost is the
# sum over nest levels of the cheapest FLAT phase cost on each level's
# own tier Topology — the same per-tier selection the engine performs —
# plus a small per-phase driver-chaining overhead that grows with nest
# depth. On a one-tier Topology (no ``groups`` attribute, or a single
# host) the models price themselves out (infinite), so AUTO picks
# hierarchical exactly when a MeshTopology says a boundary tier's link
# is worth avoiding; a two-tier mesh (no ``outer`` entries) prices to
# the same number as before the nest generalization. Flat algorithms
# on a MeshTopology are priced against its ``flat_equivalent()``
# (per-tier ring-hop weighted alpha / harmonic beta), so the crossover
# the selection produces is the boundary-vs-intra beta ratio — the
# point of the subsystem.

_HIER_PHASE_ALPHAS = 3.0   # driver-side phase chaining (waitfor hops)


def _hier_mesh(topo: Topology, w: int):
    """The MeshTopology behind ``topo`` IF the call spans its full mesh
    (duck-typed — cost.py must not import accl_tpu.hier). Sub-communicator
    calls (w != mesh world) are flat by definition."""
    groups = getattr(topo, "groups", None)
    if not groups or len(groups) < 2:
        return None
    if sum(len(g) for g in groups) != w:
        return None
    return topo


def _best_flat(op: str, topo: Topology, nbytes: float, w: int) -> float:
    """Cheapest FLAT algorithm's predicted cost for one phase on one
    tier — mirrors the engine's per-phase selection (hier/engine.py)."""
    if w <= 1:
        return 0.0
    best = math.inf
    for a in VALID_ALGORITHMS.get(op, ()):  # noqa: B007
        if a == _A.HIERARCHICAL:
            continue
        if topo.supported is not None and (op, a) not in topo.supported:
            continue
        model = _MODELS.get((op, a))
        if model is None:
            continue
        best = min(best, model(topo, w, float(nbytes)))
    return best


def _hier_tiers(mesh):
    intra = mesh.intra_topology()
    inter = mesh.inter_topology()
    L = max(len(g) for g in mesh.groups)
    return intra, inter, L, mesh.n_hosts


def _hier_ladder(mesh):
    """The pricing skeleton of the recursive lowering: per grouping
    level a ``(fanout, tier Topology, aligned)`` triple innermost-first,
    plus the top-tier exchange's ``(group count, tier Topology)``.
    Fanout at the innermost level is the largest group size; deeper it
    is the largest number of sub-groups merged per group. Duck-typed:
    a mesh without ``nest()`` prices as the historical intra/inter
    pair."""
    nest_fn = getattr(mesh, "nest", None)
    tier_fn = getattr(mesh, "tier_topology", None)
    if not (callable(nest_fn) and callable(tier_fn)):
        intra, inter, L, H = _hier_tiers(mesh)
        return ([(L, intra, bool(getattr(mesh, "aligned", False)))],
                (H, inter))
    nest = nest_fn()
    levels = []
    prev = None
    for lvl, grouping in enumerate(nest):
        if prev is None:
            sizes = [len(g) for g in grouping]
        else:
            owner = {r: gi for gi, g in enumerate(grouping) for r in g}
            sizes = [0] * len(grouping)
            for p in prev:
                sizes[owner[p[0]]] += 1
        levels.append((max(sizes), tier_fn(lvl), len(set(sizes)) == 1))
        prev = grouping
    return levels, (len(nest[-1]), tier_fn(len(nest)))


def _allreduce_hier(topo: Topology, w: int, nbytes: float) -> float:
    """Aligned nests: reduce-scatter descending every level ->
    allreduce(top tier) -> allgather ascending (each boundary tier only
    ever carries its subtree's shrunk chunk, concurrently per inner
    index); otherwise reduce-to-leader descending -> allreduce(top
    leaders) -> bcast ascending (full n over each slow boundary, but
    once instead of the flat ring's repeated crossings)."""
    mesh = _hier_mesh(topo, w)
    if mesh is None:
        return math.inf
    levels, (H, top) = _hier_ladder(mesh)
    over = levels[0][1].alpha_us * (
        _HIER_PHASE_ALPHAS + 2.0 * (len(levels) - 1))
    fans = [f for f, _t, _a in levels]
    prod = 1
    for f in fans:
        prod *= f
    # the cheap aligned shape additionally needs the ELEMENT count to
    # divide by the fanout product (plan_phases falls back to the
    # leader shape per level otherwise). The model only sees bytes;
    # byte divisibility is the necessary-condition proxy (element
    # divisibility implies it), so byte-indivisible sizes are priced at
    # the leader cost they will actually pay. A byte-divisible but
    # element-indivisible size still mispredicts toward the aligned
    # cost — a bounded misprediction the EWMA refinement corrects from
    # real retire times.
    if (all(a for _f, _t, a in levels) and all(f > 1 for f in fans)
            and nbytes % prod == 0):
        cost = over
        m = float(nbytes)
        for f, tp, _a in levels:
            m = m / f
            cost += (_best_flat("reduce_scatter", tp, m, f)
                     + _best_flat("allgather", tp, m, f))
        return cost + _best_flat("allreduce", top, m, H)
    cost = over
    for f, tp, _a in levels:
        cost += (_best_flat("reduce", tp, nbytes, f)
                 + _best_flat("bcast", tp, nbytes, f))
    return cost + _best_flat("allreduce", top, nbytes, H)


def _allgather_hier(topo: Topology, w: int, nbytes: float) -> float:
    """gather ascending (leader chunks grow by the fanout per level) ->
    allgather(top tier, subtree blocks) -> bcast of the whole vector
    descending. ``nbytes`` is the per-rank chunk (the chunked-op
    convention, module docstring)."""
    mesh = _hier_mesh(topo, w)
    if mesh is None:
        return math.inf
    levels, (H, top) = _hier_ladder(mesh)
    cost = levels[0][1].alpha_us * (
        _HIER_PHASE_ALPHAS + 2.0 * (len(levels) - 1))
    m = float(nbytes)
    for f, tp, _a in levels:
        cost += _best_flat("gather", tp, m, f)
        m *= f
    cost += _best_flat("allgather", top, m, H)
    for f, tp, _a in levels:
        cost += _best_flat("bcast", tp, w * float(nbytes), f)
    return cost


def _reduce_scatter_hier(topo: Topology, w: int, nbytes: float) -> float:
    """reduce of the whole vector ascending -> reduce_scatter(top tier,
    subtree blocks) [uneven nests: allreduce(top leaders)] -> scatter
    descending (leader chunks shrink by the fanout per level).
    ``nbytes`` is the per-rank chunk."""
    mesh = _hier_mesh(topo, w)
    if mesh is None:
        return math.inf
    levels, (H, top) = _hier_ladder(mesh)
    cost = levels[0][1].alpha_us * (
        _HIER_PHASE_ALPHAS + 2.0 * (len(levels) - 1))
    total = w * float(nbytes)
    for f, tp, _a in levels:
        cost += _best_flat("reduce", tp, total, f)
    if all(a for _f, _t, a in levels):
        cost += _best_flat("reduce_scatter", top, total / H, H)
    else:
        cost += _best_flat("allreduce", top, total, H)
    m = float(nbytes)
    for f, tp, _a in levels:
        cost += _best_flat("scatter", tp, m, f)
        m *= f
    return cost


def _bcast_hier(topo: Topology, w: int, nbytes: float) -> float:
    """bcast(root -> one representative per top-tier group over the
    slowest tier) -> bcast descending the nest: the payload crosses
    each boundary tier (groups - 1) times instead of up to W - 1."""
    mesh = _hier_mesh(topo, w)
    if mesh is None:
        return math.inf
    levels, (H, top) = _hier_ladder(mesh)
    cost = levels[0][1].alpha_us * (
        _HIER_PHASE_ALPHAS + (len(levels) - 1))
    cost += _best_flat("bcast", top, nbytes, H)
    for f, tp, _a in levels:
        cost += _best_flat("bcast", tp, nbytes, f)
    return cost


_MODELS = {
    ("bcast", _A.ROUND_ROBIN): _bcast_rr,
    ("bcast", _A.TREE): _bcast_tree,
    ("scatter", _A.ROUND_ROBIN): _bcast_rr,   # strided rr sends from root
    ("gather", _A.RING): _ring_chain,
    ("gather", _A.ROUND_ROBIN): _direct_fanin,
    ("gather", _A.TREE): _gather_tree,
    ("reduce", _A.RING): _ring_chain,
    ("reduce", _A.ROUND_ROBIN): _direct_fanin,
    ("reduce", _A.TREE): _reduce_tree,
    ("allgather", _A.RING): _ring_chain,
    ("allgather", _A.ROUND_ROBIN): _allgather_direct,
    ("allgather", _A.RECURSIVE_DOUBLING): _allgather_rd,
    # RING and FUSED_RING share one expansion (expand_allreduce_ring);
    # the epsilon nudge makes AUTO surface the canonical FUSED_RING name
    ("allreduce", _A.RING): lambda t, w, n: 1.0001 * _allreduce_fused(
        t, w, n),
    ("allreduce", _A.FUSED_RING): _allreduce_fused,
    ("allreduce", _A.NON_FUSED): _allreduce_nonfused,
    ("allreduce", _A.RECURSIVE_DOUBLING): _allreduce_rd,
    ("reduce_scatter", _A.RING): _ring_chain,
    ("reduce_scatter", _A.RECURSIVE_DOUBLING): _reduce_scatter_rh,
    # algorithm-less ops carry AUTO on every tier; keyed so predict_us /
    # predict_quantized_us / rank_wire price them without special cases
    ("alltoall", _A.AUTO): _alltoall_us,
    ("alltoallv", _A.AUTO): _alltoallv_us,
    ("bcast", _A.HIERARCHICAL): _bcast_hier,
    ("allgather", _A.HIERARCHICAL): _allgather_hier,
    ("allreduce", _A.HIERARCHICAL): _allreduce_hier,
    ("reduce_scatter", _A.HIERARCHICAL): _reduce_scatter_hier,
}


def predict_us(op: str, algorithm: CollectiveAlgorithm, topo: Topology,
               nbytes: int, world_size: int | None = None) -> float:
    """Predicted call time in microseconds for one (op, algorithm) pair.

    On a two-tier MeshTopology, FLAT algorithms are priced against the
    mesh's ``flat_equivalent()`` link figures when the call spans the
    full mesh (a tier-blind schedule pays the slow tier on the hops
    that cross hosts), and against the intra tier for sub-communicator
    calls (the hierarchical engine's phases run inside one tier; the
    outer phase is priced explicitly by the hierarchical models).
    HIERARCHICAL itself sees the raw mesh."""
    w = world_size if world_size is not None else topo.world_size
    if w <= 1:
        return 0.0
    alg = _A(algorithm)
    model = _MODELS.get((op, alg))
    if model is None:
        raise KeyError(f"no cost model for ({op}, "
                       f"{_A(algorithm).name})")
    groups = getattr(topo, "groups", None)
    if groups and len(groups) > 1 and alg != _A.HIERARCHICAL:
        topo = (topo.flat_equivalent()
                if sum(len(g) for g in groups) == w
                else topo.intra_topology(w))
    return model(topo, w, float(nbytes))


# -- quantized-wire variants (accl_tpu/quant.py, EQuARX arXiv 2506.17615) --
#
# A quantized variant of any algorithm moves ``1/wire_ratio`` of the
# bytes (beta scales UP by the ratio — the ACCL+ framing of compression
# as bandwidth) and pays a gamma term: the quantize/dequantize passes
# over the uncompressed payload at ``quant_gbps`` plus a fixed
# ``quant_alpha_us``. On a mesh only the BOUNDARY tiers' betas scale
# (the host boundary plus any coarser ``outer`` levels) — the per-tier
# quantize predicate never compresses intra phases (full precision by
# contract), so the model prices what the engine runs. The resulting
# crossover is the
# point: quantized wire wins exactly where wire bytes dominate, never
# in the alpha-dominated small-call band (pinned by tests/test_quantize).

def wire_byte_ratio(u_bytes: int = 4, q_bytes: int = 1,
                    block: int = 128) -> float:
    """Uncompressed-to-quantized wire byte ratio including the per-block
    f32 scale overhead (~3.87x for f32 -> fp8 at block 128)."""
    return float(u_bytes) / (float(q_bytes) + 4.0 / float(block))


def _scale_boundary_betas(topo: Topology, r: float) -> Topology:
    """Every boundary tier's beta scaled by the wire ratio — the
    ``inter_*`` host boundary plus any coarser ``outer`` TierSpec
    levels (duck-typed; plain two-tier meshes have ``outer == ()``)."""
    topo = dataclasses.replace(
        topo, inter_beta_gbps=getattr(topo, "inter_beta_gbps", 0.1) * r)
    outer = getattr(topo, "outer", ())
    if outer:
        topo = dataclasses.replace(
            topo, outer=tuple(
                dataclasses.replace(s, beta_gbps=s.beta_gbps * r)
                for s in outer))
    return topo


def predict_quantized_us(op: str, algorithm: CollectiveAlgorithm,
                         topo: Topology, nbytes: int,
                         world_size: int | None = None,
                         ratio: float | None = None) -> float:
    """Predicted microseconds for the BLOCK_SCALED variant of one
    (op, algorithm) pair."""
    r = wire_byte_ratio() if ratio is None else float(ratio)
    w = world_size if world_size is not None else topo.world_size
    if w <= 1:
        return 0.0
    groups = getattr(topo, "groups", None)
    if _A(algorithm) == _A.HIERARCHICAL and groups and len(groups) > 1:
        # per-tier quantized mode: only the boundary tiers' wires
        # quantize (intra phases stay full precision by contract), and
        # only the boundary phases' payload pays the codec
        topo_q = _scale_boundary_betas(topo, r)
        L = max(len(g) for g in groups)
        outer_bytes = (float(nbytes) / L
                       if getattr(topo, "aligned", False) and L > 1
                       else float(nbytes))
        gamma = 2.0 * outer_bytes / (topo.quant_gbps * 1e3)
    else:
        topo_q = dataclasses.replace(topo, beta_gbps=topo.beta_gbps * r)
        if groups:
            topo_q = _scale_boundary_betas(topo_q, r)
        gamma = 2.0 * float(nbytes) / (topo.quant_gbps * 1e3)
    return (predict_us(op, algorithm, topo_q, nbytes, world_size)
            + topo.quant_alpha_us + gamma)


def rank_wire(op: str, topo: Topology, nbytes: int,
              world_size: int | None = None, ratio: float | None = None
              ) -> tuple[bool, CollectiveAlgorithm | None]:
    """(quantize?, best algorithm under that wire): True exactly when
    the cheapest quantized variant beats the cheapest full-precision
    one. Deterministic in its inputs — every rank of a collective must
    agree."""
    if op in WIRE_PRICED_OPS:
        # no algorithm axis: rank the one AUTO-priced variant directly
        plain = [(_A.AUTO, predict_us(op, _A.AUTO, topo, nbytes,
                                      world_size))]
    else:
        plain = rank_algorithms(op, topo, nbytes, world_size)
    if not plain:
        return False, None
    scored = []
    for a, _c in plain:
        q = predict_quantized_us(op, a, topo, nbytes, world_size, ratio)
        if math.isfinite(q):
            scored.append((q, int(a), a))
    if not scored:
        return False, plain[0][0]
    scored.sort()
    best_q = scored[0]
    if best_q[0] < plain[0][1]:
        return True, best_q[2]
    return False, plain[0][0]


def rank_algorithms(op: str, topo: Topology, nbytes: int,
                    world_size: int | None = None
                    ) -> list[tuple[CollectiveAlgorithm, float]]:
    """Every legal algorithm of ``op`` the topology's engines implement,
    with its predicted cost, cheapest first. Ties break toward the lower
    enum value (deterministic across runs and ranks — every rank of a
    collective must pick the same algorithm from the same inputs)."""
    valid = VALID_ALGORITHMS.get(op)
    if not valid:
        return []
    scored = [(a, predict_us(op, a, topo, nbytes, world_size))
              for a in sorted(valid)
              if topo.supported is None or (op, a) in topo.supported]
    scored.sort(key=lambda p: (p[1], int(p[0])))
    return scored


def recommend_segment_size(topo: Topology, preferred: int,
                           overhead_fraction: float = 0.1,
                           floor: int = 4096,
                           overlap_depth: float | None = None) -> int:
    """Smallest power-of-two segment whose per-segment ``alpha`` overhead
    is at most ``overhead_fraction`` of its wire time, clamped to
    ``[floor, preferred]``.

    ``preferred`` is the backend's ``preferred_segment_size()`` — the
    largest segment it can accept (rx-buffer bound on the emulator tiers).
    High-alpha fabrics want segments as large as allowed; low-alpha/high-
    beta fabrics can afford smaller segments (better pipelining overlap,
    reference dma_mover segmentation) without drowning in per-segment cost.

    Overlap-aware effective beta: with a segment-streamed dataplane
    (``overlap_depth``, defaulting to ``topo.pipeline_depth``) the
    per-segment alpha of ~depth lanes is paid concurrently, so the
    *effective* per-segment overhead is ``alpha/depth`` — the pipeline
    sustains close to wire beta down to segments depth× smaller. Smaller
    segments in turn deepen the recv→combine→relay overlap, which is
    exactly what the streamed executor converts into throughput; a
    store-and-forward engine (depth 1) keeps the conservative sizing.
    """
    depth = max(1.0, (topo.pipeline_depth if overlap_depth is None
                      else overlap_depth))
    if preferred <= floor:
        return preferred
    target = (topo.alpha_us / depth) / overhead_fraction \
        * topo.beta_gbps * 1e3
    seg = 1 << max(1, math.ceil(math.log2(max(target, 1.0))))
    return max(floor, min(seg, preferred))
