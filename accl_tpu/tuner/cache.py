"""Versioned JSON tuning-table persistence.

A tuning table is the distilled output of a measurement sweep
(``python -m benchmarks --tune``): one chosen algorithm per
``(op, world_size, nbytes-bucket)`` key, plus the topology it was measured
on. Loading a table pins those choices in a :class:`~accl_tpu.tuner.Tuner`
so production runs skip both the cost model and exploration for covered
keys — the NCCL tuning-file workflow.

Schema (``SCHEMA_VERSION`` guards it):

.. code-block:: json

    {"version": 1,
     "topology": {"world_size": 4, "alpha_us": 20.0, "beta_gbps": 4.0,
                  "incast": 2.0, "tier": "emu"},
     "entries": [{"op": "allreduce", "world": 4, "bucket": 21,
                  "algorithm": "FUSED_RING", "expected_us": 1834.2,
                  "samples": 6}]}

The default path comes from the ``ACCL_TPU_TUNING_CACHE`` environment
variable, so a fleet can point every job at a shared table without code
changes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile

from ..constants import CollectiveAlgorithm
from .cost import Topology

__all__ = ["SCHEMA_VERSION", "ENV_VAR", "default_cache_path",
           "save", "load", "load_into"]

SCHEMA_VERSION = 1
ENV_VAR = "ACCL_TPU_TUNING_CACHE"


def default_cache_path() -> str | None:
    """The ``ACCL_TPU_TUNING_CACHE`` override, or None."""
    return os.environ.get(ENV_VAR) or None


def save(tuner, path: str | None = None) -> str:
    """Serialize ``tuner.entries()`` to ``path`` (default: the env
    override). Atomic: writes a sibling temp file and renames, so a
    reader never sees a torn table."""
    path = path or default_cache_path()
    if not path:
        raise ValueError(
            f"no tuning-cache path: pass one or set ${ENV_VAR}")
    doc = {"version": SCHEMA_VERSION, "entries": tuner.entries()}
    if tuner.topology is not None:
        doc["topology"] = dataclasses.asdict(tuner.topology)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load(path: str | None = None, strict: bool = False) -> dict:
    """Read and validate a tuning table; returns the parsed document.

    A wrong ``version`` (or a structurally alien file) raises when
    ``strict`` else returns an empty table — a stale cache must not take
    a production job down.
    """
    path = path or default_cache_path()
    if not path:
        raise ValueError(
            f"no tuning-cache path: pass one or set ${ENV_VAR}")
    with open(path) as f:
        doc = json.load(f)
    if (not isinstance(doc, dict)
            or doc.get("version") != SCHEMA_VERSION
            or not isinstance(doc.get("entries"), list)):
        if strict:
            raise ValueError(
                f"{path}: tuning-table version "
                f"{doc.get('version') if isinstance(doc, dict) else '?'} "
                f"incompatible with schema {SCHEMA_VERSION}")
        return {"version": SCHEMA_VERSION, "entries": []}
    return doc


def load_into(tuner, path: str | None = None, strict: bool = False) -> int:
    """Pin a saved table's entries into ``tuner``; adopts the table's
    topology when the tuner has none. Returns the number of entries
    pinned (0 for a version-incompatible table unless ``strict``).

    Tier guard: a table measured on one fabric tier must not pin
    decisions on another (an emulator-measured winner reflects 20 us
    thread-handoff hops, not 1 us ICI hops). When both the tuner and the
    table carry a topology and the tiers differ, nothing is pinned —
    raise instead under ``strict``.
    """
    doc = load(path, strict=strict)
    topo = doc.get("topology")
    table_tier = topo.get("tier") if isinstance(topo, dict) else None
    if tuner.topology is None and isinstance(topo, dict):
        try:
            tuner.topology = Topology(**topo)
        except TypeError:
            pass  # foreign topology fields: selection still works
    elif (tuner.topology is not None and table_tier
            and tuner.topology.tier != table_tier):
        if strict:
            raise ValueError(
                f"tuning table was measured on tier '{table_tier}' but "
                f"this tuner runs on '{tuner.topology.tier}'")
        return 0
    n = 0
    for e in doc["entries"]:
        try:
            tuner.pin(e["op"], e["world"], e["bucket"],
                      CollectiveAlgorithm[e["algorithm"]])
            n += 1
        except (KeyError, TypeError, ValueError):
            if strict:
                raise
    return n
