"""The Tuner: cost-model + measurement-driven AUTO resolution.

NCCL-tuner-shaped selection for the ACCL call path: ``AUTO`` resolves per
``(op, world_size, nbytes-bucket)`` key from the alpha-beta cost model
(cost.py) seeded with the device's :class:`~accl_tpu.tuner.cost.Topology`,
and is refined online from retire-time measurements — the driver feeds
every tuned call's issue->retire duration back via :meth:`observe` (the
same done-callback mechanism :class:`~accl_tpu.tracing.Profiler` records
through), and :meth:`ingest_records` bulk-loads a Profiler's
``CallRecord`` history.

Selection policy per key:

1. a pinned entry (loaded tuning table, cache.py) wins outright;
2. a cached decision from an earlier ``select`` on the same key;
3. otherwise a fresh decision is computed (under the lock) and cached:
   with probability ``epsilon`` a uniformly random legal algorithm
   (exploration — its measurements then land against it), else the
   argmin over per-algorithm scores — the EWMA of measured durations
   when an algorithm has ``min_samples`` observations, the cost-model
   prediction when it does not. Mixing the two scales works because
   both are microseconds of the same call.

Decisions are STICKY until :meth:`refresh` drops them: every rank of a
collective must expand the same algorithm or the move programs mismatch
(a ring member rendezvousing with a direct sender hangs in recv), so a
decision may not flip while calls are in flight just because a new
measurement landed between two ranks' selects. Share ONE tuner across
the ranks of an in-process world (``testing.emu_world(tuner=...)`` does)
and call :meth:`refresh` at quiesced points — after a profiled phase, an
epoch boundary — to fold the accumulated measurements (and re-roll
exploration) for subsequent phases.

Thread safety: one lock guards all mutable state; ``select`` and
``observe`` are called concurrently from every rank's worker/callback
threads of an in-process world.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import weakref

from ..constants import (CollectiveAlgorithm, DEFAULT_ALGORITHMS,
                         VALID_ALGORITHMS)
from .cost import Topology, predict_us, rank_algorithms, \
    recommend_segment_size

__all__ = ["Tuner", "nbytes_bucket"]


def nbytes_bucket(nbytes: int) -> int:
    """Power-of-two bucket index: all sizes in ``(2^(k-1), 2^k]`` share
    bucket ``k`` (0 for empty calls). Coarse enough that one measurement
    generalizes, fine enough to separate latency- from bandwidth-bound."""
    return max(0, int(nbytes) - 1).bit_length()


class _Stat:
    """EWMA + count of one (key, algorithm)'s measured durations."""

    __slots__ = ("ewma_us", "n")

    def __init__(self):
        self.ewma_us = 0.0
        self.n = 0

    def update(self, us: float, weight: float):
        self.n += 1
        if self.n == 1:
            self.ewma_us = us
        else:
            self.ewma_us += weight * (us - self.ewma_us)


class Tuner:
    """Thread-safe per-(op, world, size-bucket) algorithm selector.

    Args:
        topology: link descriptor for the cost model; when ``None`` the
            first :class:`~accl_tpu.accl.ACCL` this tuner is attached to
            binds its device's ``topology()``.
        epsilon: exploration probability (0 disables exploration; keep 0
            for deterministic multi-rank programs unless every rank shares
            ONE tuner instance — diverging per-rank choices would hang a
            rendezvous-matched tier).
        min_samples: measurements an algorithm needs before its EWMA
            replaces the cost-model prediction in scoring.
        ewma_weight: weight of the newest sample in the running average.
        seed: exploration RNG seed (deterministic tests).
    """

    def __init__(self, topology: Topology | None = None,
                 epsilon: float = 0.0, min_samples: int = 2,
                 ewma_weight: float = 0.25, seed: int = 0):
        self.topology = topology
        self.epsilon = float(epsilon)
        self.min_samples = int(min_samples)
        self.ewma_weight = float(ewma_weight)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # (op, world, bucket) -> {algorithm: _Stat}
        self._measured: dict[tuple, dict[CollectiveAlgorithm, _Stat]] = {}
        # (op, world, bucket) -> algorithm, from a loaded tuning table
        self._pinned: dict[tuple, CollectiveAlgorithm] = {}
        # (op, world, bucket) -> algorithm: sticky decisions, valid until
        # refresh() (see module docstring: rank agreement)
        self._decisions: dict[tuple, CollectiveAlgorithm] = {}
        # compiled-plan caches to invalidate when decisions may flip
        # (refresh / pin / clear_pins). Weak refs: a tuner can outlive
        # the worlds whose device caches registered with it.
        self._plan_caches: list = []
        # async calls in flight across EVERY driver sharing this tuner:
        # multi-tenant worlds share one tuner across tenants, and one
        # tenant's async storm inflating another tenant's synchronous
        # issue->retire window must not be credited to the algorithm
        # (cross-tenant EWMA contamination). Drivers bump the counter on
        # async issue/retire; training requires quiescent() — the
        # driver-local check alone only sees its OWN calls.
        self._async_inflight = 0

    # -- cross-driver quiescence (multi-tenant measurement hygiene) --------
    def note_async_issue(self):
        with self._lock:
            self._async_inflight += 1

    def note_async_retire(self):
        with self._lock:
            self._async_inflight -= 1

    def quiescent(self) -> bool:
        """True when no driver sharing this tuner has an async call in
        flight — the only state in which a synchronous call's measured
        window is attributable to its algorithm alone."""
        return self._async_inflight == 0

    # -- selection ---------------------------------------------------------
    def _topo(self, world_size: int) -> Topology:
        base = self.topology or Topology()
        if base.world_size != world_size:
            base = dataclasses.replace(base, world_size=world_size)
        return base

    def select(self, op: str, world_size: int,
               nbytes: int) -> CollectiveAlgorithm:
        """Resolve AUTO for one call. Returns AUTO itself for ops without
        an algorithm axis (send, recv, copy, ...) and for 1-rank worlds —
        the caller's static default applies."""
        valid = VALID_ALGORITHMS.get(op)
        if not valid or world_size <= 1:
            return CollectiveAlgorithm.AUTO
        key = (op, int(world_size), nbytes_bucket(nbytes))
        with self._lock:
            pinned = self._pinned.get(key)
            if pinned is not None:
                return pinned
            decided = self._decisions.get(key)
            if decided is None:
                decided = self._decide(key, op, world_size, nbytes, valid)
                self._decisions[key] = decided
            return decided

    def _decide(self, key: tuple, op: str, world_size: int, nbytes: int,
                valid) -> CollectiveAlgorithm:
        """Compute one key's decision (lock held)."""
        topo = self._topo(world_size)
        if self.epsilon > 0 and self._rng.random() < self.epsilon:
            # exploration draws only from algorithms the tier's engines
            # implement (Topology.supported) — exploring an algorithm the
            # peer daemon rejects would fail every call of the bucket —
            # AND whose predicted cost is finite: an infinite price
            # marks an algorithm no execution path can honor here
            # (HIERARCHICAL on a one-tier topology / sub-communicator),
            # which the driver would silently substitute with the flat
            # default, wasting the exploration epoch on a mislabeled
            # measurement stream
            import math as _math
            cands = sorted(a for a in valid
                           if (topo.supported is None
                               or (op, a) in topo.supported)
                           and _math.isfinite(predict_us(
                               op, a, topo, nbytes, world_size)))
            if cands:
                pick = self._rng.choice(cands)
                # exploration cost is observable process-wide: each pick
                # shows up in ACCL.metrics_snapshot() next to the plan
                # cache invalidations/misses it may trigger at refresh
                from ..tracing import METRICS
                METRICS.inc("tuner_exploration_picks_total", op=op,
                            world=world_size, algorithm=pick.name)
                return pick
        stats = self._measured.get(key, {})
        best, best_score = None, None
        for alg, predicted in rank_algorithms(op, topo, nbytes,
                                              world_size):
            st = stats.get(alg)
            score = (st.ewma_us if st is not None
                     and st.n >= self.min_samples else predicted)
            if best_score is None or score < best_score:
                best, best_score = alg, score
        if best is None:  # no cost model either: static default
            best = DEFAULT_ALGORITHMS.get(op, CollectiveAlgorithm.AUTO)
        return best

    # -- quantized-wire selection (accl_tpu/quant.py) ----------------------
    def select_wire(self, op: str, world_size: int, nbytes: int,
                    ratio: float | None = None) -> bool:
        """True when the block-scaled quantized wire variant wins for
        this (op, world, size): measured wire EWMAs (both variants
        sampled >= min_samples, fed by :meth:`observe_wire` /
        benchmarks/tune.py's wire sweep) beat the cost model, which
        otherwise prices the variants analytically (rank_wire, cost.py)
        — bandwidth-bound calls quantize, latency-bound calls never do.
        Sticky per bucket like algorithm decisions (every rank of a
        collective must agree), dropped by :meth:`refresh`."""
        from .cost import WIRE_PRICED_OPS, rank_wire
        if (op not in VALID_ALGORITHMS and op not in WIRE_PRICED_OPS) \
                or world_size <= 1:
            # algorithm-less exchanges (alltoall/alltoallv) have no
            # VALID_ALGORITHMS row but still carry a wire decision
            return False
        key = ("wire", op, int(world_size), nbytes_bucket(nbytes))
        with self._lock:
            decided = self._decisions.get(key)
            if decided is None:
                stats = self._measured.get(key, {})
                qs, ps = stats.get(True), stats.get(False)
                if (qs is not None and ps is not None
                        and qs.n >= self.min_samples
                        and ps.n >= self.min_samples):
                    decided = qs.ewma_us < ps.ewma_us
                else:
                    decided = rank_wire(op, self._topo(world_size),
                                        nbytes, world_size, ratio)[0]
                self._decisions[key] = decided
            return bool(decided)

    def observe_wire(self, op: str, world_size: int, nbytes: int,
                     quantized: bool, duration_s: float,
                     error_word: int = 0) -> bool:
        """Feed one retired call's duration under its wire variant
        (quantized = BLOCK_SCALED ran). The per-bucket EWMA pair
        replaces the analytic crossover once both variants have
        evidence. Failed calls are ignored, like :meth:`observe`."""
        from .cost import WIRE_PRICED_OPS
        if (error_word or world_size <= 1
                or (op not in VALID_ALGORITHMS
                    and op not in WIRE_PRICED_OPS)):
            return False
        key = ("wire", op, int(world_size), nbytes_bucket(nbytes))
        with self._lock:
            stats = self._measured.setdefault(key, {})
            stats.setdefault(bool(quantized), _Stat()).update(
                duration_s * 1e6, self.ewma_weight)
        return True

    def recommend_quant_block(self, nbytes: int) -> int:
        """Scale-block size for a block-scaled call of ``nbytes``
        (uncompressed payload): larger payloads amortize toward larger
        blocks (the 4-byte scale per block is pure overhead), small
        ones keep fine-grained scales for dynamic-range tracking.
        Deterministic in nbytes, so every rank derives the same block."""
        if nbytes >= 8 << 20:
            return 256
        if nbytes >= 128 << 10:
            return 128
        return 64

    # -- RMA eager/rendezvous crossover (accl_tpu/rma) ---------------------
    RMA_EAGER_MIN_B = 4 << 10
    RMA_EAGER_MAX_B = 256 << 10

    def recommend_rma_eager_max(self) -> int:
        """Byte threshold below which a one-sided put should go EAGER
        (single frame through the target's rx pool) instead of
        RENDEZVOUS (RTS/CTS, then segments landing directly in the
        window). Priced analytically from the topology — rendezvous
        pays one extra control round trip (~2*alpha_us) before any
        payload moves, eager pays the rx-pool staging copy (~nbytes at
        the link's beta) — and refined by measured put latencies fed
        through :meth:`observe_rma_put`: once both variants of a size
        bucket have ``min_samples`` observations, the measured winner
        moves the crossover. Clamped to [4 KiB, 256 KiB], floored to a
        power of two, sticky until :meth:`refresh` (the engine reads it
        per transfer; a mid-flight flip is harmless — the plan kind is
        carried in the opening frame — but determinism helps tests).
        ``$ACCL_TPU_RMA_EAGER_MAX`` still wins when set: the engine
        consults the tuner only when neither the constructor nor the
        environment pinned a threshold."""
        key = ("rma_eager_max",)
        with self._lock:
            decided = self._decisions.get(key)
            if decided is not None:
                return int(decided)
            topo = self.topology or Topology()
            cross = 2.0 * topo.alpha_us * topo.beta_gbps * 1e3
            eager_win, rdv_win = [], []
            for k, stats in self._measured.items():
                if not (len(k) == 2 and k[0] == "rma_eager"):
                    continue
                e, r = stats.get(True), stats.get(False)
                if (e is None or r is None or e.n < self.min_samples
                        or r.n < self.min_samples):
                    continue
                size = 1 << int(k[1])  # bucket upper bound, bytes
                (eager_win if e.ewma_us <= r.ewma_us
                 else rdv_win).append(size)
            if rdv_win:
                # conservative: stay below the smallest size where
                # rendezvous measurably wins, whatever the model says
                cross = min(cross, min(rdv_win) / 2)
            clean = [s for s in eager_win
                     if not rdv_win or s < min(rdv_win)]
            if clean:
                cross = max(cross, max(clean))
            cross = max(self.RMA_EAGER_MIN_B,
                        min(self.RMA_EAGER_MAX_B, int(cross)))
            cross = 1 << (cross.bit_length() - 1)  # power-of-two floor
            self._decisions[key] = cross
            return cross

    def observe_rma_put(self, nbytes: int, eager: bool,
                        duration_s: float, error_word: int = 0) -> bool:
        """Feed one retired put's issue->land latency under the variant
        it actually ran (True = eager). The engine feeds only CLEAN
        zero-retry puts — a retried transfer's latency measures the
        fault, not the variant. Evidence moves the crossover at the
        next quiesced :meth:`refresh`, not mid-decision."""
        if error_word or nbytes <= 0 or duration_s < 0:
            return False
        key = ("rma_eager", nbytes_bucket(nbytes))
        with self._lock:
            stats = self._measured.setdefault(key, {})
            stats.setdefault(bool(eager), _Stat()).update(
                duration_s * 1e6, self.ewma_weight)
        return True

    def refresh(self):
        """Drop cached decisions: the next ``select`` per key re-scores
        with the measurements accumulated so far (and re-rolls
        exploration). Call only at quiesced points — no collective may be
        in flight while decisions flip (module docstring). Registered
        compiled-plan caches are invalidated: a flipped decision expands
        a different program, and stale entries for the old algorithm
        must not accumulate (they can never be SERVED stale — plan keys
        carry the concrete algorithm — but observability wants the
        re-resolution counted)."""
        with self._lock:
            self._decisions.clear()
        self._invalidate_plan_caches("tuner")

    # -- compiled-plan cache coupling --------------------------------------
    def register_plan_cache(self, cache):
        """Attach a device's :class:`~accl_tpu.plancache.PlanCache`: it is
        invalidated whenever this tuner's decisions may change
        (``refresh``, ``pin``, ``clear_pins``), and its counters surface
        through :meth:`plan_cache_stats`. Held weakly — caches die with
        their worlds."""
        ref = weakref.ref(cache)
        with self._lock:
            if any(r() is cache for r in self._plan_caches):
                return
            self._plan_caches = [r for r in self._plan_caches
                                 if r() is not None]
            self._plan_caches.append(ref)

    def _invalidate_plan_caches(self, reason: str):
        with self._lock:
            refs = list(self._plan_caches)
        for r in refs:
            cache = r()
            if cache is not None:
                cache.invalidate(reason)

    def plan_cache_stats(self) -> dict:
        """Aggregate counters over every live registered plan cache —
        the tuner-side observability of exploration cost (each
        epsilon-greedy re-roll that flips an algorithm shows up as an
        invalidation plus a run of misses)."""
        agg = {"caches": 0, "entries": 0, "hits": 0, "misses": 0,
               "bypasses": 0, "evictions": 0, "invalidations": {}}
        with self._lock:
            refs = list(self._plan_caches)
        for r in refs:
            cache = r()
            if cache is None:
                continue
            st = cache.stats()
            agg["caches"] += 1
            for k in ("entries", "hits", "misses", "bypasses",
                      "evictions"):
                agg[k] += st[k]
            for reason, n in st["invalidations"].items():
                agg["invalidations"][reason] = \
                    agg["invalidations"].get(reason, 0) + n
        return agg

    # -- online refinement -------------------------------------------------
    def observe(self, op: str, world_size: int, nbytes: int,
                algorithm: CollectiveAlgorithm, duration_s: float,
                error_word: int = 0) -> bool:
        """Feed one retired call's measured duration. Failed calls and
        AUTO-labeled records (nothing concrete to credit) are ignored.
        Returns True iff the measurement was credited."""
        alg = CollectiveAlgorithm(algorithm)
        if (error_word or alg == CollectiveAlgorithm.AUTO
                or op not in VALID_ALGORITHMS or world_size <= 1):
            return False
        key = (op, int(world_size), nbytes_bucket(nbytes))
        with self._lock:
            stats = self._measured.setdefault(key, {})
            stats.setdefault(alg, _Stat()).update(duration_s * 1e6,
                                                  self.ewma_weight)
        return True

    def ingest_records(self, records, world_size: int,
                       world_by_comm: dict[int, int] | None = None) -> int:
        """Bulk-load :class:`~accl_tpu.tracing.CallRecord` history (records
        carry the concrete algorithm the call ran; "AUTO"/"" labels are
        skipped). Returns how many records were usable.

        Records only carry ``comm_id``, not the communicator's size —
        pass ``world_by_comm`` (comm_id -> size, e.g. built from
        ``ACCL.communicators``) when the history includes split-
        communicator collectives, or their durations would be mis-keyed
        under the world size. Unknown comm_ids fall back to
        ``world_size``."""
        world_by_comm = world_by_comm or {}
        n = 0
        for r in records:
            alg_name = getattr(r, "algorithm", "")
            try:
                alg = CollectiveAlgorithm[alg_name]
            except KeyError:
                continue
            if alg == CollectiveAlgorithm.AUTO:
                continue  # backend-internal choice: nothing to credit
            w = world_by_comm.get(getattr(r, "comm_id", 0), world_size)
            if self.observe(r.op, w, r.nbytes, alg, r.duration_s,
                            getattr(r, "error_word", 0)):
                n += 1
        return n

    # -- segment sizing ----------------------------------------------------
    def recommend_segment_size(self, preferred: int) -> int:
        """Segment size for this tuner's topology, bounded by the
        backend's ``preferred_segment_size()`` (passed as ``preferred``)."""
        return recommend_segment_size(self.topology or Topology(),
                                      preferred)

    # -- table import/export (cache.py serializes these) -------------------
    def pin(self, op: str, world_size: int, bucket: int,
            algorithm: CollectiveAlgorithm):
        """Force one key's selection (loaded tuning-table entry). The
        (op, algorithm) pair must be legal — a pin that check_algorithm
        would reject later must fail HERE, at load time, not on every
        call of the op."""
        alg = CollectiveAlgorithm(algorithm)
        if alg not in VALID_ALGORITHMS.get(op, frozenset()):
            raise ValueError(
                f"cannot pin {alg.name} for {op}: not a legal algorithm")
        with self._lock:
            self._pinned[(op, int(world_size), int(bucket))] = alg
        self._invalidate_plan_caches("tuner")

    def clear_pins(self):
        """Drop loaded tuning-table pins (a re-tune must measure from
        scratch, not echo the stale table back)."""
        with self._lock:
            self._pinned.clear()
        self._invalidate_plan_caches("tuner")

    def entries(self) -> list[dict]:
        """Current decisions as serializable rows: one per key that has a
        pin or at least one measured algorithm, ``expected_us`` being the
        winning score (pinned entries re-export with their measured EWMA
        when one exists, else 0)."""
        with self._lock:
            # 3-tuple algorithm keys and 4-tuple ("wire", ...) keys sort
            # together safely: position 0 is a string either way and no
            # op is named "wire"
            keys = sorted(set(self._pinned) | set(self._measured))
            out = []
            for key in keys:
                if len(key) != 3:
                    continue  # ("wire", ...) variant stats: not a table
                    # row (select_wire reads them directly)
                op, world, bucket = key
                stats = self._measured.get(key, {})
                pinned = self._pinned.get(key)
                if pinned is not None:
                    st = stats.get(pinned)
                    choice, score = pinned, (st.ewma_us if st else 0.0)
                    samples = st.n if st else 0
                else:
                    choice, score, samples = None, None, 0
                    for alg in sorted(stats):
                        st = stats[alg]
                        if st.n >= self.min_samples and (
                                score is None or st.ewma_us < score):
                            choice, score, samples = alg, st.ewma_us, st.n
                    if choice is None:
                        continue  # nothing trustworthy to persist
                out.append({"op": op, "world": world, "bucket": bucket,
                            "algorithm": choice.name,
                            "expected_us": round(float(score), 3),
                            "samples": samples})
            return out

    def clear_measurements(self):
        with self._lock:
            self._measured.clear()

    def describe(self) -> str:
        rows = [f"{'op':<16}{'W':>4}{'bucket':>8}{'algorithm':>14}"
                f"{'us':>12}{'n':>5}"]
        for e in self.entries():
            rows.append(f"{e['op']:<16}{e['world']:>4}{e['bucket']:>8}"
                        f"{e['algorithm']:>14}{e['expected_us']:>12.1f}"
                        f"{e['samples']:>5}")
        return "\n".join(rows)
